"""Device KV arena: the data plane of the paged cache.

Replaces the reference's torch slab + in-place writes
(/root/reference/src/bloombee/server/memory_cache_manager.py:1373 `_write_kvs`,
paged_kv.py:137-204 page-at-a-time writes) with functional jnp ops designed to
live *inside* the jitted span step: the arena is a donated carry, writes are
scatters, reads are page gathers. XLA turns the donated scatter into an
in-place HBM update — the slab-write-vs-cat win of the reference's arch reform
(tests/bench_arch_reform.py) is the default here.

Layout: per layer, a flat slot dimension of num_pages * page_size tokens:
    k, v: [L, num_pages * page_size, n_kv_heads, head_dim]
Slot ids come from the host-side PagedKVTable (page * page_size + offset).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_arena(
    num_layers: int,
    num_pages: int,
    page_size: int,
    n_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quant: str | None = None,
) -> dict:
    """quant="int4": store the slabs group-quantized (the reference's
    TorchCompressedDevice KV capacity lever, compression.py:22-210) — ~3.2x
    more tokens per HBM byte; writes quantize and reads dequantize inside
    the jitted span step."""
    shape = (num_layers, num_pages * page_size, n_kv_heads, head_dim)
    if quant == "int4":
        from bloombee_tpu.kv.quant import make_quant_slab

        return {"k": make_quant_slab(shape), "v": make_quant_slab(shape)}
    if quant not in (None, "none"):
        raise ValueError(f"unknown KV quant mode {quant!r}")
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def arena_write(
    k_layer: jax.Array,  # [S_tot, n_kv, hd] one layer's slab
    v_layer: jax.Array,
    slots: jax.Array,  # [N] int32 flat slot ids
    k_new: jax.Array,  # [N, n_kv, hd]
    v_new: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Scatter new KV rows into a layer slab (functional; donate the slab).

    Out-of-bounds slot ids are dropped — the span step points padding rows at
    slot == num_slots to discard their writes.
    """
    from bloombee_tpu.kv.quant import QuantSlab, quantize

    if isinstance(k_layer, QuantSlab):
        new_k, new_v = quantize(k_new), quantize(v_new)
        k_layer = QuantSlab(
            *(
                a.at[slots].set(b, mode="drop")
                for a, b in zip(k_layer, new_k)
            )
        )
        v_layer = QuantSlab(
            *(
                a.at[slots].set(b, mode="drop")
                for a, b in zip(v_layer, new_v)
            )
        )
        return k_layer, v_layer
    k_layer = k_layer.at[slots].set(k_new.astype(k_layer.dtype), mode="drop")
    v_layer = v_layer.at[slots].set(v_new.astype(v_layer.dtype), mode="drop")
    return k_layer, v_layer


def gather_pages(
    layer_slab: jax.Array,  # [S_tot, n_kv, hd]
    page_table: jax.Array,  # [B, max_pages] int32
    page_size: int,
) -> jax.Array:
    """Gather each sequence's pages: returns [B, max_pages*page_size, n_kv, hd].

    Invalid (padding) pages gather garbage rows; callers mask by context
    length — the clamped-read invariant lives in the attention mask, mirroring
    the reference's gather_prefix clamp (paged_kv.py:265-316).
    """
    from bloombee_tpu.kv.quant import QuantSlab, dequantize

    b, max_pages = page_table.shape
    slots = (
        page_table[:, :, None] * page_size
        + jnp.arange(page_size, dtype=page_table.dtype)[None, None, :]
    ).reshape(b, max_pages * page_size)
    if isinstance(layer_slab, QuantSlab):
        gathered = QuantSlab(*(leaf[slots] for leaf in layer_slab))
        return dequantize(gathered, jnp.float32)
    return layer_slab[slots]


def arena_reorder(
    k_layer: jax.Array,
    v_layer: jax.Array,
    src_slots: jax.Array,  # [N] gather sources (surviving speculative slots)
    dst_slots: jax.Array,  # [N] scatter destinations (compacted prefix slots)
) -> tuple[jax.Array, jax.Array]:
    """Compact surviving speculative KV rows onto the committed prefix.

    The reference does this with a background reorder thread
    (memory_cache_manager.py:2011-2160 update_cache_and_async_reorder); here it
    is a single on-device gather+scatter fused into the step that needs it —
    SURVEY.md section 7 'hard parts' #2 recommends exactly this.
    `src_slots == dst_slots` rows are no-ops (gather-before-scatter semantics:
    all reads happen from the pre-update slab).
    """
    k_rows = k_layer[src_slots]
    v_rows = v_layer[src_slots]
    return k_layer.at[dst_slots].set(k_rows), v_layer.at[dst_slots].set(v_rows)
