"""Swarm traffic simulator: conductor semantics, scenario gates, and the
anti-vacuity proof that the gates can actually fail.

These run in tier-1 only (the whole point of the virtual clock is that a
thousand virtual seconds cost wall milliseconds); the chaos matrix's SIM
entry exercises the same scenarios through the shipped gate itself,
``python -m bloombee_tpu.sim --require --smoke`` — deliberately NOT by
replaying this file, which would double-pay its wall cost for zero new
coverage.
"""

import asyncio
import json
import time

import pytest

from bloombee_tpu.sim.cost import CostModel
from bloombee_tpu.sim.engine import SimEngine
from bloombee_tpu.sim.scenarios import SCENARIOS, run_scenario
from bloombee_tpu.utils import clock as vclock

# The scenario gates define "healthy" for STOCK control-plane tuning; the
# chaos matrix replays these tests under entries that deliberately warp
# that tuning (BBTPU_ADMIT_HIGH_MS=400, BBTPU_MEASURED_REBALANCE=0, ...),
# which would make a red un-attributable. Pin every knob the scenarios'
# physics depends on back to its declared default. The anti-vacuity test
# then re-warps exactly one knob on purpose.
_STOCK_TUNING = [
    "BBTPU_ADMIT", "BBTPU_ADMIT_HIGH_MS", "BBTPU_ADMIT_RETRY_MS",
    "BBTPU_ADMIT_WINDOW_S", "BBTPU_MEASURED_REBALANCE",
    "BBTPU_PROMOTE_HIGH_MS", "BBTPU_PROMOTE_SUSTAIN_S",
    "BBTPU_MIXED_BATCH", "BBTPU_SPEC_BATCH", "BBTPU_BATCH_WINDOW_MS",
    "BBTPU_CHUNK_AGE_S", "BBTPU_KEEPALIVE_S", "BBTPU_CLOCK_SCALE",
    "BBTPU_SIM_SESSIONS", "BBTPU_SIM_SEED", "BBTPU_SIM_COST_JSON",
    "BBTPU_SIM_SETTLE_S", "BBTPU_SIM_RETRY_AMP_MAX",
    "BBTPU_SIM_SHED_AMP_MAX", "BBTPU_SIM_FLAP_MAX",
    "BBTPU_SIM_PROMOTE_LATENCY_S", "BBTPU_SIM_WALL_BUDGET_S",
]


@pytest.fixture(autouse=True)
def _stock_tuning(monkeypatch):
    for name in _STOCK_TUNING:
        monkeypatch.delenv(name, raising=False)


# --------------------------------------------------------------- conductor


def test_engine_advances_virtual_time_for_free():
    """Sleepers wake in deadline order at exact virtual instants, and
    minutes of virtual time cost (well under) seconds of wall time."""
    eng = SimEngine(start=100.0)
    woke = []

    async def sleeper(tag, dur):
        await vclock.async_sleep(dur)
        woke.append((tag, eng.now()))

    async def main(engine):
        tasks = [
            asyncio.ensure_future(sleeper("slow", 250.0)),
            asyncio.ensure_future(sleeper("fast", 100.0)),
        ]
        await engine.run_tasks(tasks, max_virtual_s=1000.0, max_wall_s=30.0)

    w0 = time.perf_counter()
    eng.run(main)
    wall = time.perf_counter() - w0
    assert woke == [("fast", 200.0), ("slow", 350.0)]
    assert eng.advances >= 2
    assert wall < 5.0, f"350 virtual seconds cost {wall:.1f}s wall"


def test_counting_executor_delivers_compute_at_virtual_cost():
    """A cost-model compute (thread-side ``clock.sleep``) completes at
    exactly submit-time + cost, and the single sim worker serializes
    submissions — the conductor never advances past in-flight compute."""
    eng = SimEngine(start=0.0)

    async def main(engine):
        ex = engine.new_executor()

        def compute(cost):
            vclock.sleep(cost)
            return engine.now()

        async def one(cost):
            return await asyncio.wrap_future(ex.submit(compute, cost))

        tasks = [
            asyncio.ensure_future(one(5.0)),
            asyncio.ensure_future(one(3.0)),
        ]
        await engine.run_tasks(tasks, max_virtual_s=100.0, max_wall_s=30.0)
        return [t.result() for t in tasks]

    # one worker: the 3.0 job queues behind the 5.0 job, finishing at 8.0
    assert eng.run(main) == [5.0, 8.0]


def test_stall_detection_fails_loudly():
    """Live tasks with no virtual sleeper is a deadlock in the code under
    test; the conductor must raise, not hang CI."""
    from bloombee_tpu.sim.engine import SimStalled

    eng = SimEngine()

    async def main(engine):
        blocked = asyncio.ensure_future(asyncio.Event().wait())
        try:
            await engine.run_tasks([blocked], max_wall_s=1.0)
        finally:
            blocked.cancel()

    with pytest.raises(SimStalled):
        eng.run(main)


# --------------------------------------------------------------- scenarios


def test_flash_crowd_smoke_passes_gates_with_real_shedding():
    """Healthy stock tuning rides out the crowd: every gate green, and
    the overload machinery demonstrably engaged (sheds, abandons, naive
    retries) — a run where nothing shed would prove nothing."""
    rep = run_scenario("flash_crowd", sessions=200, seed=0)
    m = rep["metrics"]
    assert rep["failures"] == [], rep["failures"]
    assert m["completed"] == m["sessions"]
    assert m["shed_total"] > 0, "crowd never tripped admission control"
    assert m["abandons"] > 0, "no naive client abandoned a slow prefill"
    assert m["retry_amplification"] > 1.0
    assert m["shed_retry_amplification"] >= m["retry_amplification"]


def test_span_loss_smoke_promotes_standby():
    rep = run_scenario("span_loss", sessions=120, seed=0)
    m = rep["metrics"]
    assert rep["failures"] == [], rep["failures"]
    assert m["completed"] == m["sessions"]
    assert m["promotions"] >= 1, "correlated crash never promoted standby"


def test_diurnal_smoke_rebalances():
    rep = run_scenario("diurnal", sessions=120, seed=0)
    m = rep["metrics"]
    assert rep["failures"] == [], rep["failures"]
    assert m["completed"] == m["sessions"]
    assert m["rebalances_moved"] >= 1, (
        "skewed diurnal load never triggered a measured rebalance"
    )


def test_mistuned_retry_hint_trips_metastable_gate(monkeypatch):
    """Anti-vacuity: the gates must be able to FAIL. With the admission
    Retry-After hint floored to 1ms, naive crowd clients re-enter in
    lockstep, abandoned prefills keep burning queue, and the retry storm
    sustains itself — the amplification gates must go red."""
    monkeypatch.setenv("BBTPU_ADMIT_RETRY_MS", "1")
    rep = run_scenario("flash_crowd", sessions=200, seed=0)
    assert rep["failures"], (
        "BBTPU_ADMIT_RETRY_MS=1 passed every gate — the simulator can no "
        "longer distinguish a metastable swarm from a healthy one"
    )
    assert any("attempts" in f or "amplification" in f
               for f in rep["failures"]), rep["failures"]


def test_scenario_catalog_is_stable():
    assert list(SCENARIOS) == ["flash_crowd", "span_loss", "diurnal"]


# -------------------------------------------------------------- cost model


def test_cost_model_fits_bench_json(tmp_path):
    data = {
        "chain": {"steps_per_sec": 20.0},
        "prefill": {"ttft_ms": 500.0, "prompt_tokens": 100},
    }
    path = tmp_path / "bench.json"
    path.write_text(json.dumps(data))
    m = CostModel.from_bench_json(str(path), num_blocks=4)
    # 50ms/step minus dispatch (2ms) and wire rtt (10ms), over 4 blocks
    assert m.decode_row_ms_per_block == pytest.approx(38.0 / 4)
    assert m.prefill_tok_ms_per_block == pytest.approx(488.0 / (100 * 4))
    # tolerant fitter: an empty / alien bench JSON keeps the defaults
    d = CostModel.from_bench_json({})
    assert d.decode_row_ms_per_block == CostModel().decode_row_ms_per_block
    assert d.prefill_tok_ms_per_block == CostModel().prefill_tok_ms_per_block
