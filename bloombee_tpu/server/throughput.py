"""Server throughput measurement + announcement.

Port of /root/reference/src/bloombee/server/throughput.py:44-345: measure
real decode steps through the span executor, cache the result on disk keyed
by (model, span, dtype, device), and fold it into the announced ServerInfo
so client routing can rank servers. Timing uses the scalar-fetch fence
(block_until_ready is unreliable on tunneled PJRT backends).
"""

from __future__ import annotations

import hashlib
import json
import logging
import pathlib

import numpy as np

from bloombee_tpu.utils import clock

logger = logging.getLogger(__name__)

CACHE_PATH = pathlib.Path.home() / ".cache" / "bloombee_tpu" / "throughput.json"


def _cache_key(server) -> str:
    import jax

    raw = json.dumps(
        [
            server.model_uid,
            server.start_block,
            server.end_block,
            str(server.executor.compute_dtype),
            str(jax.devices()[0]),
        ]
    )
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


def _load_cache() -> dict:
    try:
        with open(CACHE_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def _store_cache(cache: dict) -> None:
    CACHE_PATH.parent.mkdir(parents=True, exist_ok=True)
    with open(CACHE_PATH, "w") as f:
        json.dump(cache, f)


async def measure_and_announce(server, batch: int = 1, steps: int = 8) -> float:
    """Measure (or load cached) inference rps and fold into announcements."""
    import jax.numpy as jnp

    key = _cache_key(server)
    cache = _load_cache()
    if key in cache:
        rps = cache[key]
        logger.info("throughput cache hit: %.2f rps", rps)
    else:
        from bloombee_tpu.server.compute_queue import PRIORITY_TRAINING

        d = server.spec.hidden_size
        async with server.manager.allocate(batch, steps + 8) as handle:
            hidden = np.zeros((batch, 1, d), np.float32)
            # route through the compute queue: it is the single serialization
            # point for device work and the shared donated KV arena
            await server.compute.submit(
                PRIORITY_TRAINING, server.executor.decode, handle, hidden
            )  # compile
            # real wall time on purpose: this is a hardware measurement
            # (announced rps), not a timing decision — a scaled test
            # clock must not inflate it
            t0 = clock.perf_counter()
            out = None
            for _ in range(steps):
                out = await server.compute.submit(
                    PRIORITY_TRAINING, server.executor.decode, handle, hidden
                )
            float(jnp.sum(jnp.asarray(out)))  # fence
            rps = steps / max(clock.perf_counter() - t0, 1e-9)
        cache[key] = rps
        try:
            _store_cache(cache)
        except Exception as e:
            logger.warning("throughput cache store failed: %s", e)
        logger.info("measured %.2f inference rps", rps)
    server.throughput = rps
    server.inference_rps = rps
    return rps
