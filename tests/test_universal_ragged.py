"""Universal ragged dispatch (ISSUE 17): decode rows + tree-verify rows +
one prefill chunk fused into ONE device step.

Covers the tentpole claims end to end: fused super-batches are numerically
identical to the members dispatched solo (property test over explicit and
randomized kind mixes), a TP-mesh span — previously on the unsupported
list — executes `ragged_group` with parity against the single-chip
executor, per-kind rollback survives a fault injected AFTER the device
step wrote every member's KV (decodes roll back, the chunk truncates, tree
members truncate — then solo replays reproduce the exact pre-fault
outputs), e2e universal traffic (concurrent decode + spec-decode + long
chunked prefill) stays HF-greedy-exact while cross-kind dispatches
actually happen, warmup pre-compiles the unified buckets so steady-state
fused traffic incurs ZERO recompiles (jitwatch --require), declined
ragged configs surface per-reason in rpc_info (BB006), and the kind-aware
group_hint bounds tree gathers by the speculating-session count.
"""

import asyncio
import types

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp
import jax.random as jr

from bloombee_tpu.kv.cache_manager import CacheManager
from bloombee_tpu.models.llama.block import init_block_params
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.parallel.serving import make_serving_mesh
from bloombee_tpu.runtime.executor import SpanExecutor
from bloombee_tpu.server.block_server import (
    BlockServer,
    _BatchMember,
    _ChunkMember,
    _Session,
    _TreeMember,
)
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.utils import jitwatch
from bloombee_tpu.utils.tree import stack_params
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.rpc import connect

SPEC = ModelSpec(
    family="llama", hidden_size=64, intermediate_size=128,
    num_attention_heads=4, num_key_value_heads=2, head_dim=16,
    num_hidden_layers=3, vocab_size=64,
)


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


def _params():
    return stack_params([
        init_block_params(jr.PRNGKey(i), SPEC)
        for i in range(SPEC.num_hidden_layers)
    ])


def _rand_tree(rng, t):
    """A random linearized speculative tree: node j's parent has a lower
    index, the mask row is ancestors-or-self, depths are rotary offsets."""
    mask = np.zeros((t, t), dtype=bool)
    depth = np.zeros((t,), dtype=np.int32)
    mask[0, 0] = True
    for j in range(1, t):
        p = int(rng.integers(0, j))
        mask[j] = mask[p]
        mask[j, j] = True
        depth[j] = depth[p] + 1
    return mask[None], depth[None]


def _make_member(rng, kind):
    """(hidden, tree_mask, depths) for one member of the given kind."""
    d = SPEC.hidden_size

    def h(t):
        return (rng.standard_normal((1, t, d)) * 0.1).astype(np.float32)

    if kind == "decode":
        return h(1), None, None
    if kind == "tree":
        t = int(rng.choice([3, 5, 7]))
        mask, depth = _rand_tree(rng, t)
        return h(t), mask, depth
    assert kind == "chunk"
    return h(int(rng.integers(3, 7))), None, None


async def _fused_vs_solo(mix, seed, mesh=None, return_fused=False):
    """Allocate one session per member, prefill random contexts, dispatch
    each member SOLO (single-member ragged group — the legacy per-kind
    program), rewind, then dispatch them all FUSED; returns the per-member
    (solo, fused) output pairs."""
    rng = np.random.default_rng(seed)
    manager = CacheManager(
        num_layers=SPEC.num_hidden_layers, num_pages=64, page_size=4,
        n_kv_heads=SPEC.num_key_value_heads, head_dim=SPEC.head_dim,
        dtype=jnp.float32,
    )
    ex = SpanExecutor(
        _params(), SPEC, manager, compute_dtype=jnp.float32, mesh=mesh
    )
    from contextlib import AsyncExitStack

    async with AsyncExitStack() as stack:
        handles = []
        for _ in mix:
            handles.append(await stack.enter_async_context(
                manager.allocate(1, 32, timeout=5.0)
            ))
        hiddens, masks, depths = [], [], []
        for h, kind in zip(handles, mix):
            ctx = int(rng.integers(4, 10))
            ex.prefill(
                h,
                (rng.standard_normal((1, ctx, SPEC.hidden_size)) * 0.1)
                .astype(np.float32),
            )
            hid, tm, dp = _make_member(rng, kind)
            hiddens.append(hid)
            masks.append(tm)
            depths.append(dp)
        snaps = [
            [int(x) for x in manager.context_lens(h)] for h in handles
        ]

        solo = []
        for h, hid, tm, dp, snap in zip(
            handles, hiddens, masks, depths, snaps
        ):
            out, _ = ex.ragged_group(
                [h], [hid], tree_masks=[tm], depths_list=[dp]
            )
            solo.append(np.asarray(out))
            manager.truncate_speculative(h, snap)

        out, _ = ex.ragged_group(
            handles, hiddens, tree_masks=masks, depths_list=depths
        )
        out = np.asarray(out)
        fused = []
        off = 0
        for hid in hiddens:
            t = int(hid.shape[1])
            fused.append(out[off:off + t])
            off += t
        for h, snap in zip(handles, snaps):
            manager.truncate_speculative(h, snap)
        if return_fused:
            return fused
        return list(zip(solo, fused))


# ------------------------------------------------ fused == solo, per kind
@pytest.mark.parametrize("mix", [
    ["decode", "decode", "decode"],        # pure-decode fast path
    ["decode", "chunk"],                   # Sarathi fused iteration
    ["decode", "tree"],                    # cross-kind: NEW to ISSUE 17
    ["tree", "tree", "chunk"],             # trees + chunk: NEW
    ["decode", "decode", "tree", "chunk"], # the full universal mix
], ids=lambda m: "+".join(m))
def test_fused_matches_solo(mix):
    """ONE ragged dispatch over mixed row kinds is numerically identical
    to each member dispatched alone (causal rows ride the tree-mask
    variant as lower-triangular rows — exactly causality)."""
    pairs = asyncio.run(_fused_vs_solo(mix, seed=7))
    for solo, fused in pairs:
        np.testing.assert_allclose(solo, fused, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("seed", [11, 23, 41])
def test_fused_matches_solo_fuzz(seed):
    """Property fuzz: random member-kind mixes (always >= 2 members, at
    most one chunk) stay solo-identical under fusion."""
    rng = np.random.default_rng(seed)
    mix = (
        ["decode"] * int(rng.integers(0, 3))
        + ["tree"] * int(rng.integers(0, 3))
        + (["chunk"] if rng.integers(0, 2) else [])
    )
    while len(mix) < 2:
        mix.append("decode")
    pairs = asyncio.run(_fused_vs_solo(mix, seed=seed))
    for solo, fused in pairs:
        np.testing.assert_allclose(solo, fused, atol=1e-5, rtol=1e-5)


# ------------------------------------------------------ TP-mesh burn-down
def test_tp_mesh_ragged_group_parity():
    """The first unsupported-list entry burned down: a TP-mesh span runs
    the universal ragged dispatch (replicated payload, GSPMD-sharded dense
    attend) with parity against the single-chip executor — including the
    cross-kind decode+tree+chunk mix."""
    mix = ["decode", "tree", "chunk"]
    ref = asyncio.run(_fused_vs_solo(mix, seed=13, return_fused=True))
    tp2 = asyncio.run(_fused_vs_solo(
        mix, seed=13, mesh=make_serving_mesh(2), return_fused=True
    ))
    for a, b in zip(ref, tp2):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_tp_mesh_not_on_unsupported_list():
    manager = CacheManager(
        num_layers=SPEC.num_hidden_layers, num_pages=16, page_size=4,
        n_kv_heads=SPEC.num_key_value_heads, head_dim=SPEC.head_dim,
        dtype=jnp.float32,
    )
    ex = SpanExecutor(
        _params(), SPEC, manager, compute_dtype=jnp.float32,
        mesh=make_serving_mesh(2),
    )
    assert ex.ragged_unsupported(has_tree=False) is None
    assert ex.ragged_unsupported(has_tree=True) is None
    assert ex.mixed_unsupported() is None
    assert ex.tree_group_unsupported() is None


# ---------------------------------------------------------- server fixture
@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_uniragged")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


async def _uni_server(model_dir, reg_port, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_batch", 8)
    s = BlockServer(
        model_uid="tiny", start=0, end=3, model_dir=model_dir,
        registry=RegistryClient("127.0.0.1", reg_port), **kw,
    )
    await s.start()
    return s


# ------------------------------------------- per-kind rollback, post-write
@pytest.mark.chaos
def test_fault_after_device_write_rolls_back_per_kind(
    tiny_model_dir, monkeypatch
):
    """Inject a fault AFTER the fused device step wrote every member's KV:
    the decode member must roll back, the chunk member truncate to its
    pre-dispatch snapshot, the tree member truncate its rows — and the
    per-kind solo replays must then reproduce EXACTLY the outputs of solo
    dispatches taken from the clean pre-fault state (a rollback that
    leaked one ghost token would shift every replayed position)."""
    model_dir, _, config = tiny_model_dir
    d = config.hidden_size

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = await _uni_server(
            model_dir, reg.port, mixed_batch=True, spec_batch=True,
            prefill_chunk=4,
        )
        try:
            rng = np.random.default_rng(3)
            async with s.manager.allocate(1, 32, timeout=5.0) as h_dec, \
                    s.manager.allocate(1, 32, timeout=5.0) as h_tree, \
                    s.manager.allocate(1, 32, timeout=5.0) as h_chunk:
                handles = (h_dec, h_tree, h_chunk)
                for h in handles:
                    s.executor.prefill(
                        h,
                        (rng.standard_normal((1, 6, d)) * 0.1)
                        .astype(np.float32),
                    )
                sessions = [
                    _Session(f"rb-{i}", h, 1)
                    for i, h in enumerate(handles)
                ]
                for sess in sessions:
                    sess.adoption_settled = True
                dec_hid = (rng.standard_normal((1, 1, d)) * 0.1).astype(
                    np.float32
                )
                mask, depth = _rand_tree(rng, 5)
                tree_hid = (rng.standard_normal((1, 5, d)) * 0.1).astype(
                    np.float32
                )
                chunk_hid = (rng.standard_normal((1, 4, d)) * 0.1).astype(
                    np.float32
                )
                snaps = [
                    [int(x) for x in s.manager.context_lens(h)]
                    for h in handles
                ]

                # clean-state solo references, state rewound after each
                ref_dec, _ = s._compute_step(
                    sessions[0], h_dec, dec_hid, False, None
                )
                ref_dec = np.asarray(ref_dec)
                s.manager.truncate_speculative(h_dec, snaps[0])
                ref_tree, _ = s._compute_step(
                    sessions[1], h_tree, tree_hid, False, mask, depth
                )
                ref_tree = np.asarray(ref_tree)
                s.manager.truncate_speculative(h_tree, snaps[1])
                ref_chunk, _ = s._compute_prefill_chunk(
                    sessions[2], h_chunk, chunk_hid, True, False
                )
                ref_chunk = np.asarray(ref_chunk)
                s.manager.truncate_speculative(h_chunk, snaps[2])

                # the fused dispatch faults AFTER its device write landed
                orig = s.executor.ragged_group
                calls = {"n": 0}

                def flaky(*a, **kw):
                    out = orig(*a, **kw)
                    calls["n"] += 1
                    raise RuntimeError("injected post-write fault")

                monkeypatch.setattr(s.executor, "ragged_group", flaky)
                members = [
                    _BatchMember(sessions[0], h_dec, dec_hid),
                    _TreeMember(sessions[1], h_tree, tree_hid, mask, depth),
                    _ChunkMember(
                        sessions[2], h_chunk, chunk_hid, True, False
                    ),
                ]
                outs = s._compute_ragged_group(members)
                assert calls["n"] == 1
                assert not any(isinstance(o, Exception) for o in outs)
                got_dec = np.asarray(outs[0][0])
                got_tree = np.asarray(outs[1][0])
                got_chunk = np.asarray(outs[2][0])
                np.testing.assert_allclose(
                    got_dec, ref_dec, atol=1e-5, rtol=1e-5
                )
                np.testing.assert_allclose(
                    got_tree, ref_tree, atol=1e-5, rtol=1e-5
                )
                np.testing.assert_allclose(
                    got_chunk, ref_chunk, atol=1e-5, rtol=1e-5
                )
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run())


# --------------------------------------------- e2e universal traffic, HF
def test_e2e_universal_traffic_hf_exact(tiny_model_dir, monkeypatch):
    """Concurrent decode + spec-decode + long chunked prefill on a server
    with BOTH flags on: cross-kind fused dispatches actually happen
    (ragged_cross_kind_dispatches > 0), every stream stays HF-greedy
    exact, and the unified counters ride rpc_info."""
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel

    model_dir, hf_model, config = tiny_model_dir
    # three continuously-stepping streams co-arrive within ms; a modest
    # window fuses them without long tail stalls when one stream finishes
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "300")

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = await _uni_server(
            model_dir, reg.port, mixed_batch=True, spec_batch=True,
            prefill_chunk=4,
        )
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        rng = np.random.default_rng(29)
        dec_prompt = rng.integers(0, config.vocab_size, size=(1, 5))
        spec_prompt = rng.integers(0, config.vocab_size, size=(1, 6))
        long_ids = (np.arange(24)[None, :] * 5 + 3) % config.vocab_size
        info = None
        try:
            generated = []

            async def decode_loop():
                async with model.inference_session(40, 1) as sess:
                    out = await sess.step(model.embed(dec_prompt))
                    tok = np.argmax(model.logits(out)[:, -1], axis=-1)
                    generated.append(tok)
                    for _ in range(11):
                        out = await sess.step(
                            model.embed(generated[-1][:, None])
                        )
                        generated.append(
                            np.argmax(model.logits(out)[:, -1], axis=-1)
                        )

            async def spec_loop():
                return await generate_speculative(
                    model,
                    GreedyTreeDrafter(
                        LocalJaxDraftModel.from_dir(model_dir),
                        branching=(2, 1),
                    ),
                    spec_prompt, max_new_tokens=8,
                )

            async def long_prefill():
                async with model.inference_session(40, 1) as sess:
                    out = await sess.step(model.embed(long_ids))
                    t = np.argmax(model.logits(out)[:, -1], axis=-1)
                    got = [t]
                    for _ in range(2):
                        out = await sess.step(model.embed(t[:, None]))
                        t = np.argmax(model.logits(out)[:, -1], axis=-1)
                        got.append(t)
                    return np.concatenate(got)

            _, spec_ids, long_tail = await asyncio.gather(
                decode_loop(), spec_loop(), long_prefill()
            )

            # fused dispatches crossed row kinds at least once
            assert s.ragged_group_dispatches > 0
            assert s.ragged_cross_kind_dispatches > 0
            assert s.step_dispatches > 0

            # every stream HF-exact
            ref = _hf_greedy(hf_model, dec_prompt, len(generated))
            np.testing.assert_array_equal(
                np.concatenate(generated), ref[0, dec_prompt.shape[1]:]
            )
            ref = _hf_greedy(
                hf_model, spec_prompt,
                np.asarray(spec_ids).shape[1] - spec_prompt.shape[1],
            )
            np.testing.assert_array_equal(np.asarray(spec_ids), ref)
            ref = _hf_greedy(hf_model, long_ids, 3)
            np.testing.assert_array_equal(
                long_tail, ref[0, long_ids.shape[1]:]
            )

            conn = await connect("127.0.0.1", s.port)
            info, _ = await conn.call("rpc_info", {})
            await conn.close()
        finally:
            await s.stop()
            await reg.stop()
        assert info["ragged_group_dispatches"] == s.ragged_group_dispatches
        assert (
            info["ragged_cross_kind_dispatches"]
            == s.ragged_cross_kind_dispatches
        )
        assert info["ragged_declines"] == {}

    asyncio.run(run())


# --------------------------------------------------- jitwatch steady gate
@pytest.mark.chaos
def test_e2e_universal_zero_steady_recompiles(
    tiny_model_dir, monkeypatch, tmp_path
):
    """Warmup pre-compiles the UNIFIED buckets (packed decode pair,
    decode+chunk, tree pair, decode+tree, decode+tree+chunk); steady-state
    fused traffic constrained to those buckets must incur ZERO recompiles
    and the flushed report must pass jitwatch --require."""
    monkeypatch.setenv("BBTPU_JITWATCH", "1")
    model_dir, _, config = tiny_model_dir
    d = config.hidden_size
    report = tmp_path / "uniragged_jitwatch.jsonl"
    jitwatch.reset()
    # earlier tests may have compiled these shapes in-process; drop the
    # executable cache so warmup's compiles actually happen
    jax.clear_caches()

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = await _uni_server(
            model_dir, reg.port, mixed_batch=True, spec_batch=True,
            prefill_chunk=4,
        )
        try:
            await s.warmup(batch_sizes=(1, 2), prefill_tokens=8)
            snap = jitwatch.snapshot()
            assert snap["fenced"] is True
            assert snap["warmup_compiles"] >= 1, snap

            # steady state: drive the group runners directly with members
            # shaped exactly like the warmed buckets (ctx 8 prefill, tree
            # t=11 — the default-drafter node count — chunk = the 4-token
            # budget); every bucket tag must hit the warm cache
            rng = np.random.default_rng(1)
            async with s.manager.allocate(1, 36, timeout=5.0) as h_a, \
                    s.manager.allocate(1, 36, timeout=5.0) as h_b, \
                    s.manager.allocate(1, 36, timeout=5.0) as h_c:
                handles = (h_a, h_b, h_c)
                for h in handles:
                    s.executor.prefill(
                        h,
                        (rng.standard_normal((1, 8, d)) * 0.1)
                        .astype(np.float32),
                    )
                sessions = [
                    _Session(f"jw-{i}", h, 1)
                    for i, h in enumerate(handles)
                ]
                for sess in sessions:
                    sess.adoption_settled = True

                def dec(sess, h):
                    return _BatchMember(
                        sess, h,
                        (rng.standard_normal((1, 1, d)) * 0.1)
                        .astype(np.float32),
                    )

                def tree(sess, h):
                    t_i = 11
                    mask = np.tril(np.ones((1, t_i, t_i), dtype=bool))
                    depth = np.arange(t_i, dtype=np.int32)[None, :]
                    return _TreeMember(
                        sess, h,
                        (rng.standard_normal((1, t_i, d)) * 0.1)
                        .astype(np.float32),
                        mask, depth,
                    )

                def chunk(sess, h):
                    return _ChunkMember(
                        sess, h,
                        (rng.standard_normal((1, 4, d)) * 0.1)
                        .astype(np.float32),
                        True, False,
                    )

                groups = [
                    [dec(sessions[0], h_a), dec(sessions[1], h_b)],
                    [dec(sessions[0], h_a), chunk(sessions[2], h_c)],
                    [tree(sessions[0], h_a), tree(sessions[1], h_b)],
                    [dec(sessions[0], h_a), tree(sessions[1], h_b)],
                    [
                        dec(sessions[0], h_a), tree(sessions[1], h_b),
                        chunk(sessions[2], h_c),
                    ],
                ]
                for group in groups:
                    snaps = [
                        [int(x) for x in s.manager.context_lens(m.handle)]
                        for m in group
                    ]
                    outs = s._compute_ragged_group(group)
                    assert not any(
                        isinstance(o, Exception) for o in outs
                    ), outs
                    # rewind speculative members so contexts stay in the
                    # warmed page buckets round after round (decode rows
                    # COMMIT on success — their few extra tokens stay
                    # within the same pow2 page bucket)
                    for m, sn in zip(group, snaps):
                        if not isinstance(m, _BatchMember):
                            s.manager.truncate_speculative(m.handle, sn)
                assert s.ragged_cross_kind_dispatches >= 2
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run())

    snap = jitwatch.snapshot()
    assert snap["steady_state_recompiles"] == 0, [
        c for c in snap["compiles"] if c["phase"] == "steady"
    ]
    jitwatch.flush(str(report))
    assert jitwatch._main([str(report), "--require"]) == 0
    # under scripts/chaos.sh the same line feeds the UNIRAGGED entry gate
    jitwatch.flush()
    jitwatch.reset()


# ------------------------------------------------ decline surfacing, hint
def test_ragged_declines_surface_in_rpc_info(tiny_model_dir, monkeypatch):
    """BB006: a span that can't run the ragged path records a per-reason
    decline when the operator asked for fusing, visible in rpc_info."""
    model_dir, _, _ = tiny_model_dir
    monkeypatch.setattr(
        SpanExecutor, "ragged_unsupported",
        lambda self, has_tree=False: "weight offload",
    )

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = await _uni_server(
            model_dir, reg.port, mixed_batch=True, spec_batch=True,
        )
        try:
            assert s.mixed_batch is False
            assert s.spec_batch is False
            assert s.ragged_declines == {"weight offload": 2}
            conn = await connect("127.0.0.1", s.port)
            info, _ = await conn.call("rpc_info", {})
            await conn.close()
            assert info["ragged_declines"] == {"weight offload": 2}
            assert info["ragged_group_dispatches"] == 0
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run())


def test_group_hint_is_kind_aware(tiny_model_dir):
    """The PR-13 early-dispatch extension: a tree-only gather is bounded
    by the speculating-session count (non-speculating sessions can't
    contribute tree rows), a causal gather excludes speculating sessions,
    and with both flags on every open session counts."""
    model_dir, _, _ = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = await _uni_server(model_dir, reg.port, spec_batch=True)
        try:
            sessions = {
                sid: _Session(sid, None, 1)
                for sid in ("a", "b", "c")
            }
            # a revealed itself non-speculating; b, c still could
            sessions["a"].speculating = False
            s._sessions = sessions
            tree_m = types.SimpleNamespace(key=("tree", None, None, "f32"))
            dec_m = types.SimpleNamespace(
                key=("decode1", None, None, "f32")
            )
            assert s._batch_group_hint() == 3  # no members: total
            assert s._batch_group_hint([tree_m]) == 2  # b, c only
            assert s._batch_group_hint([dec_m]) == 1  # a only
            s.mixed_batch = True  # both flags: every kind fuses
            assert s._batch_group_hint([tree_m]) == 3
        finally:
            s._sessions = {}  # fabricated sessions have no real handles
            await s.stop()
            await reg.stop()

    asyncio.run(run())
