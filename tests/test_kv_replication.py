"""Fast failover: incremental KV checkpoint replication to standbys.

The correctness bar (ISSUE 4): with replication on, killing the primary
mid-decode must recover token-identically to an uninterrupted greedy run
while replaying at most one replication interval plus the unsealed tail
(counter-asserted); mixed swarms (standby without support, replication
off) must degrade byte-for-byte to today's full-history replay; kv_put
installs only into prefix pools as evictable refcount-0 pages; and
embed-less (hidden-history) sessions probe-and-skip on recovery too.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.client.session import InferenceSession
from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
from bloombee_tpu.kv.paged import PagedKVTable
from bloombee_tpu.kv.prefix import hidden_hash_chain, page_hash_chain
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.faults import FaultPlan, FaultRule
from bloombee_tpu.wire.rpc import connect
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_repl")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("prefix_cache", True)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


def _assert_no_leaks(server):
    table = server.manager.table
    c = table.counts()
    assert c["free"] + c["referenced"] + c["cached"] == table.num_pages, c
    assert c["referenced"] == 0, c


async def _greedy_decode(model, session, out, n, dtype=np.int64):
    """Decode `n` greedy tokens from the last-position output `out`,
    stepping EVERY token (so its page count is deterministic at the call
    boundary); returns (new_ids [B, n], out). Mirrors model.generate's
    loop but lets a test split one generation around a mid-decode kill."""
    new = np.zeros((out.shape[0], 0), dtype=dtype)
    for _ in range(n):
        logits = model.logits(out[:, -1:])[:, 0]
        nxt = np.argmax(logits, axis=-1).astype(dtype)[:, None]
        new = np.concatenate([new, nxt], axis=1)
        out = await session.step(model.embed(nxt), ids=nxt)
    return new, out


async def _wait_installed(standby, pages, timeout_s=10.0):
    """Poll until the standby's prefix pool holds `pages` replicated
    pages (replication is asynchronous on the primary)."""
    for _ in range(int(timeout_s / 0.05)):
        if standby.manager.prefix_stats()["repl_pages_installed"] >= pages:
            return
        await asyncio.sleep(0.05)
    raise AssertionError(
        f"standby never installed {pages} pages: "
        f"{standby.manager.prefix_stats()}"
    )


# ------------------------------------------------------------------- units
def test_hidden_hash_chain_shapes_and_roots():
    rng = np.random.default_rng(0)
    hidden = rng.standard_normal((10, 8)).astype(np.float32)
    chain = hidden_hash_chain(hidden, 4)
    assert len(chain) == 2  # one digest per FULL page only
    # incremental extension never rehashes sealed pages
    partial = hidden_hash_chain(hidden[:8], 4)
    assert hidden_hash_chain(hidden, 4, chain=partial) == chain
    # chained: a different second page changes digest 2, not digest 1
    other = hidden.copy()
    other[7] += 1.0
    chain2 = hidden_hash_chain(other, 4)
    assert chain2[0] == chain[0] and chain2[1] != chain[1]
    # distinct root from id chains: equal byte content can never alias
    ids_chain = page_hash_chain(list(range(8)), 4)
    assert set(ids_chain).isdisjoint(hidden_hash_chain(hidden[:8], 4))
    with pytest.raises(ValueError):
        hidden_hash_chain(hidden[0], 4)  # rows must be [T, D]


def test_install_cached_evictable_never_referenced():
    t = PagedKVTable(num_pages=3, page_size=4)
    p = t.install_cached("h1")
    assert p is not None and t._pool["h1"] == p
    assert t.install_cached("h1") is None  # duplicate: no-op
    c = t.counts()
    assert (c["free"], c["referenced"], c["cached"]) == (2, 0, 1)
    # referenced pages are never stolen: with 2 pages pinned by a live
    # sequence, installs churn through the single remaining page
    t.add_seq(0)
    t.reserve(0, 8)
    assert t.install_cached("h2") is not None
    assert t.install_cached("h3") is not None  # evicts the coldest ("h1")
    c = t.counts()
    assert (c["free"], c["referenced"], c["cached"]) == (0, 2, 1)
    assert "h1" not in t._pool and "h3" in t._pool
    # fully-referenced table: install declines instead of stealing
    t2 = PagedKVTable(num_pages=1, page_size=4)
    t2.add_seq(0)
    t2.reserve(0, 4)
    assert t2.install_cached("h") is None


def test_kv_put_declines_on_unsupported_server(tiny_model_dir):
    """kv_put against a server without the prefix cache (and against a
    mismatched page geometry) declines with installed=0 + reason instead
    of erroring — the mixed-swarm contract."""
    model_dir, _, _ = tiny_model_dir

    async def run():
        s_off = _server(model_dir, None, 0, 3, prefix_cache=False)
        s_on = _server(model_dir, None, 0, 3)
        for s in (s_off, s_on):
            await s.start()
        k = np.zeros((1, 3, 4, 2, 16), np.float32)
        payload = {"page_size": 4, "start": 0, "end": 3, "hashes": ["h"]}
        try:
            conn = await connect("127.0.0.1", s_off.port)
            meta, _ = await conn.call("kv_put", payload, [k, k])
            assert meta["installed"] == 0 and "unsupported" in meta["reason"]
            await conn.close()

            conn = await connect("127.0.0.1", s_on.port)
            meta, _ = await conn.call(
                "kv_put", {**payload, "page_size": 8}, [k, k]
            )
            assert meta["installed"] == 0 and "page_size" in meta["reason"]
            meta, _ = await conn.call(
                "kv_put", {**payload, "end": 2}, [k, k]
            )
            assert meta["installed"] == 0 and "span" in meta["reason"]
            await conn.close()
        finally:
            for s in (s_off, s_on):
                await s.stop()

    asyncio.run(run())


# ------------------------------------------------------------- failover e2e
@pytest.mark.chaos
def test_failover_replays_one_interval_token_identical(tiny_model_dir):
    """Primary dies mid-decode with replication on: the client recovers
    onto the standby, the probe adopts the replicated pages, and the
    replay is bounded by one replication interval + the unsealed tail —
    while the full generation stays token-identical to HF greedy."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 3, throughput=10.0)
        s_b = _server(model_dir, rc(), 0, 3, throughput=1.0)
        for s in (s_a, s_b):
            await s.start()

        # 12-token prompt + 4 decoded = 16 tokens: exactly 4 sealed pages
        # at page_size 4, so a caught-up standby bounds the replay to the
        # skip cap's single token
        input_ids = (np.arange(12)[None, :] * 5 + 3) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 9)

        cfg = ClientConfig(
            use_push=False, prefix_cache=True, kv_repl_every=1,
            ban_timeout=0.5, ban_max=2.0,
        )
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(28, 1)
        await session.__aenter__()
        assert session._standby_peers()  # a standby was selected
        primary_port = session._spans[0].span.server_info.port
        primary = s_a if s_a.port == primary_port else s_b
        standby = s_b if primary is s_a else s_a

        out = await session.step(model.embed(input_ids), ids=input_ids)
        first, out = await _greedy_decode(
            model, session, out, 4, dtype=input_ids.dtype
        )
        # 16 committed tokens -> 4 sealed pages, all announced (interval 1)
        await _wait_installed(standby, pages=4)
        # the standby installs before the primary's kv_put reply lands, so
        # give the sender's bookkeeping a beat to catch up
        for _ in range(100):
            if primary.repl_pages_sent >= 4:
                break
            await asyncio.sleep(0.05)
        assert primary.repl_pages_sent >= 4

        # the sender-side counters ride the primary's rpc_info
        conn = await connect("127.0.0.1", primary.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["repl_pages_sent"] >= 4
        assert info["repl_lag_pages"] == 0
        assert info["kv_repl"] is True
        await conn.close()

        await primary.stop()
        rest, _ = await _greedy_decode(
            model, session, out, 5, dtype=input_ids.dtype
        )
        await session.__aexit__(None, None, None)
        np.testing.assert_array_equal(
            np.concatenate([input_ids, first, rest], axis=1), ref
        )

        # the replay was one token, not the 16-token history: 4 sealed
        # pages all matched on the standby, skip capped at len - 1
        page_size, repl_every = 4, 1
        assert 0 < session.failover_replayed_tokens
        assert session.failover_replayed_tokens < (
            page_size * repl_every + 1
        )
        # the standby (now primary) saw the same replay server-side and
        # installed the pages as evictable cached entries
        conn = await connect("127.0.0.1", standby.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["repl_pages_installed"] >= 4
        assert (
            info["failover_replayed_tokens"]
            == session.failover_replayed_tokens
        )
        await conn.close()

        await asyncio.sleep(0.2)  # server-side session teardown is async
        _assert_no_leaks(standby)
        await standby.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.chaos
def test_failover_mixed_swarm_full_replay(tiny_model_dir):
    """Standby without prefix-cache support: the client finds no capable
    standby (kv_repl not advertised), replicates nothing, and recovery
    degrades to today's full-history replay — still token-identical."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 3, throughput=10.0)
        s_b = _server(
            model_dir, rc(), 0, 3, throughput=1.0, prefix_cache=False
        )
        for s in (s_a, s_b):
            await s.start()

        input_ids = (np.arange(12)[None, :] * 7 + 1) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 9)

        cfg = ClientConfig(
            use_push=False, prefix_cache=True, kv_repl_every=1,
            ban_timeout=0.5, ban_max=2.0,
        )
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(28, 1)
        await session.__aenter__()
        assert not session._standby_peers()  # nothing capable to pick
        primary_port = session._spans[0].span.server_info.port
        primary = s_a if s_a.port == primary_port else s_b
        assert primary is s_a  # the only prefix-cache server wins routing

        out = await session.step(model.embed(input_ids), ids=input_ids)
        first, out = await _greedy_decode(
            model, session, out, 4, dtype=input_ids.dtype
        )
        assert s_b.manager.prefix_stats()["repl_pages_installed"] == 0
        assert s_a.repl_pages_sent == 0

        await primary.stop()
        rest, _ = await _greedy_decode(
            model, session, out, 5, dtype=input_ids.dtype
        )
        await session.__aexit__(None, None, None)
        np.testing.assert_array_equal(
            np.concatenate([input_ids, first, rest], axis=1), ref
        )
        # nothing was replicated, so the whole 16-token committed history
        # replayed through s_b (which can't probe: its cache is off)
        assert session.failover_replayed_tokens == 16

        await asyncio.sleep(0.2)  # server-side session teardown is async
        await s_b.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.chaos
def test_failover_hidden_history_probes_standby(tiny_model_dir):
    """Embed-less session (raw hidden steps, no ids): replication keys
    pages by hidden-byte chains, and recovery's hidden replay path now
    probes them — the standby hit trims the replay exactly like the id
    path. Post-failover outputs match an uninterrupted session."""
    model_dir, _, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 3, throughput=10.0)
        s_b = _server(model_dir, rc(), 0, 3, throughput=1.0)
        for s in (s_a, s_b):
            await s.start()
        manager = RemoteSequenceManager(rc(), "tiny", 3)

        rng = np.random.default_rng(3)
        steps = [
            rng.standard_normal((1, 12, config.hidden_size))
            .astype(np.float32) * 0.02
        ] + [
            rng.standard_normal((1, 1, config.hidden_size))
            .astype(np.float32) * 0.02
            for _ in range(9)
        ]

        # uninterrupted reference outputs for the post-failover steps
        ref_out = []
        s_ref = InferenceSession(
            manager, max_length=28, batch_size=1, prefix_cache=True,
            repl_every=0,
        )
        async with s_ref:
            for h in steps:
                ref_out.append(await s_ref.step(h))

        s = InferenceSession(
            manager, max_length=28, batch_size=1, prefix_cache=True,
            repl_every=1,
        )
        async with s:
            for h in steps[:5]:  # 12 + 4 tokens = 4 sealed pages
                await s.step(h)
            primary_port = s._spans[0].span.server_info.port
            primary = s_a if s_a.port == primary_port else s_b
            standby = s_b if primary is s_a else s_a
            await _wait_installed(standby, pages=4)
            await primary.stop()
            for i, h in enumerate(steps[5:], start=5):
                out = await s.step(h)
                np.testing.assert_allclose(
                    out, ref_out[i], rtol=0, atol=1e-4,
                    err_msg=f"step {i} diverged after failover",
                )
            # probe-and-skip on the hidden path: replay = the skip-capped
            # single token, not the 16-token history
            assert s.failover_replayed_tokens == 1

        await asyncio.sleep(0.2)  # server-side session teardown is async
        _assert_no_leaks(standby)
        await standby.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.chaos
def test_drain_flushes_replication_backlog(tiny_model_dir):
    """A draining primary (SIGTERM path) flushes pending replication to
    the standby before exiting, so sessions it abandons fail over with at
    most the unsealed tail to replay."""
    model_dir, _, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 3, throughput=10.0)
        s_b = _server(model_dir, rc(), 0, 3, throughput=1.0)
        for s in (s_a, s_b):
            await s.start()
        manager = RemoteSequenceManager(rc(), "tiny", 3)

        rng = np.random.default_rng(5)
        s = InferenceSession(
            manager, max_length=28, batch_size=1, prefix_cache=True,
            repl_every=1,
        )
        async with s:
            # the first kv_put to EITHER server resets: the primary's
            # background sweep fails and leaves the whole 4-page backlog
            # pending, so only the drain-time flush can deliver it
            plan = FaultPlan(seed=7)
            for srv in (s_a, s_b):
                plan.add(FaultRule(site="send", action="reset",
                                   method="kv_put", port=srv.port,
                                   nth=1, count=1))
            faults.set_plan(plan)
            await s.step(
                rng.standard_normal((1, 16, config.hidden_size))
                .astype(np.float32) * 0.02
            )
            primary_port = s._spans[0].span.server_info.port
            primary = s_a if s_a.port == primary_port else s_b
            standby = s_b if primary is s_a else s_a
            for _ in range(100):  # wait for the failed sweep to settle
                if ("send", "reset") in {(x, a) for x, a, _ in plan.log}:
                    break
                await asyncio.sleep(0.05)
            await asyncio.sleep(0.1)
            assert (
                standby.manager.prefix_stats()["repl_pages_installed"] == 0
            )
            assert primary._repl_lag() == 4
            # drain with the session still open: the flush must push the
            # whole backlog even though the session never closes here
            await primary.drain(timeout=0.5)
            assert (
                standby.manager.prefix_stats()["repl_pages_installed"] >= 4
            )

        await asyncio.sleep(0.2)
        await standby.stop()
        await reg.stop()

    asyncio.run(run())
