"""Falcon family: rotary + MQA/GQA, LayerNorm, parallel attention/MLP.

Reference: /root/reference/src/bloombee/models/falcon/ (WrappedFalconBlock).
Supports the falcon-7b shape: multi_query fused QKV ([H q-heads | 1 k | 1 v]
rows), parallel residual with a single shared input LayerNorm, bias-free
linears, exact-GELU 4h MLP.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.checkpoint import read_tensor as _t
from bloombee_tpu.models.spec import ModelSpec


def falcon_spec_from_hf(config: Any) -> ModelSpec:
    n_head = config.num_attention_heads
    hidden = config.hidden_size
    if getattr(config, "new_decoder_architecture", False):
        raise NotImplementedError(
            "falcon new_decoder_architecture (grouped fused-QKV layout) is "
            "not supported yet; falcon-7b-style checkpoints only"
        )
    if getattr(config, "alibi", False) or getattr(config, "bias", False):
        raise NotImplementedError(
            "falcon-rw variants (alibi/bias) are not supported yet"
        )
    n_kv = 1 if getattr(config, "multi_query", True) else n_head
    return ModelSpec(
        family="falcon",
        hidden_size=hidden,
        intermediate_size=4 * hidden,
        num_attention_heads=n_head,
        num_key_value_heads=n_kv,
        head_dim=hidden // n_head,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=getattr(config, "layer_norm_epsilon", 1e-5),
        rope_theta=getattr(config, "rope_theta", 10000.0),
        tie_word_embeddings=True,
        norm_type="ln",
        mlp_type="gelu",
        parallel_attn=getattr(config, "parallel_attn", True),
        alibi=getattr(config, "alibi", False),
    )


def _load_block(reader, layer_idx: int, dtype=None) -> dict:
    p = f"transformer.h.{layer_idx}"
    params = {
        "input_layernorm": _t(reader, f"{p}.input_layernorm.weight", dtype),
        "input_layernorm_bias": _t(reader, f"{p}.input_layernorm.bias", dtype),
    }
    n_head = reader.config["num_attention_heads"]
    d = reader.config["hidden_size"]
    head_dim = d // n_head
    n_kv = 1 if reader.config.get("multi_query", True) else n_head
    w = _t(reader, f"{p}.self_attention.query_key_value.weight", dtype)
    # rows: H query heads, then n_kv k heads, then n_kv v heads
    q_rows = n_head * head_dim
    kv_rows = n_kv * head_dim
    params["q_proj"] = w[:q_rows].T
    params["k_proj"] = w[q_rows : q_rows + kv_rows].T
    params["v_proj"] = w[q_rows + kv_rows :].T
    params["o_proj"] = _t(reader, f"{p}.self_attention.dense.weight", dtype).T
    params["up_proj"] = _t(reader, f"{p}.mlp.dense_h_to_4h.weight", dtype).T
    params["down_proj"] = _t(reader, f"{p}.mlp.dense_4h_to_h.weight", dtype).T
    return params


def _load_client(reader, dtype=None) -> dict:
    out = {
        "embed": _t(reader, "transformer.word_embeddings.weight", dtype),
        "norm": _t(reader, "transformer.ln_f.weight", dtype),
        "norm_bias": _t(reader, "transformer.ln_f.bias", dtype),
    }
    if reader.has("lm_head.weight"):
        out["lm_head"] = _t(reader, "lm_head.weight", dtype).T
    else:
        out["lm_head"] = out["embed"].T
    return out


register_family(
    Family(
        "falcon", falcon_spec_from_hf, loader=_load_block,
        client_loader=_load_client,
    )
)
