"""Declared lock hierarchy — the single source of truth.

PR 9's BB003 hard-coded three lock names; since then the tree has grown
asyncio locks in the server (session replication, the peer pool, lazy
param/pruner loads), the registry client, and the wire layer, plus two
leaf thread locks (ledger, transport stats). This module declares every
package lock ONCE with a level in the acquisition partial order, and
everything else derives from it:

- the static pass (rules.BB003/BB009) classifies ``with``/``async with``
  context expressions into declared locks via :func:`classify` and checks
  nesting against :func:`edge_allowed`;
- the runtime witness (utils/lockwatch.py) wraps the real lock objects
  under these keys and validates every OBSERVED acquisition-order edge
  against the same partial order;
- ARCHITECTURE.md's "Lock hierarchy" table is generated from
  :func:`describe` (marker-delimited like the README env table; drift
  fails the analyze gate).

Levels ascend in acquisition order: while holding a lock at level L you
may only acquire locks at a STRICTLY higher level (reentrant locks may
re-acquire themselves). Locks sharing a level are unordered peers — they
must never nest in either direction. asyncio locks sit below the thread
locks because the event loop's tasks hold them across awaits that fan
into compute-thread work; the reverse direction (thread code acquiring
an asyncio lock) is impossible by construction.

Pure stdlib — imported by the AST lint, which must never import jax.
"""

from __future__ import annotations

import dataclasses

__all__ = [
    "LockDecl",
    "HIERARCHY",
    "by_key",
    "level_of",
    "classify",
    "edge_allowed",
    "describe",
]


@dataclasses.dataclass(frozen=True)
class LockDecl:
    key: str  # stable id, e.g. "server.repl" (lockwatch + findings)
    level: int  # ascending acquisition order; equal = unordered peers
    kind: str  # "asyncio.Lock" | "threading.Lock" | "threading.RLock" | ...
    where: str  # declaring module + attribute (documentation)
    doc: str  # one line: what the lock protects / why this level
    reentrant: bool = False  # may re-acquire itself (RLock)
    # lowercase substrings that identify this lock in a with-context
    # expression (strings already stripped) in ANY file. Checked in
    # HIERARCHY order, first match wins — keep specific names before
    # generic ones.
    patterns: tuple[str, ...] = ()
    # patterns that only apply in the declaring module (for generic
    # spellings like `self._lock` that mean a DIFFERENT lock per file);
    # matched against paths ending with path_suffix, before the global
    # pattern passes
    path_suffix: str = ""
    local_patterns: tuple[str, ...] = ()


HIERARCHY: tuple[LockDecl, ...] = (
    LockDecl(
        key="server.repl",
        level=10,
        kind="asyncio.Lock",
        where="server/block_server.py _Session.repl_lock",
        doc=(
            "serializes standby-replication sweeps per session; held "
            "across compute export + peer push, so it is the OUTERMOST "
            "lock in the tree"
        ),
        patterns=("repl_lock",),
    ),
    LockDecl(
        key="server.peer_pool",
        level=20,
        kind="asyncio.Lock (per peer)",
        where="server/block_server.py _PeerPool._locks",
        doc=(
            "one connect-or-reuse critical section per outbound peer so "
            "an unreachable peer's connect timeout cannot stall pushes "
            "to healthy peers"
        ),
        patterns=("_locks",),
    ),
    LockDecl(
        key="registry.client",
        level=30,
        kind="asyncio.Lock",
        where="swarm/registry.py RegistryClient._lock",
        doc="guards the cached registry connection's connect-or-reuse",
        path_suffix="swarm/registry.py",
        local_patterns=("self._lock",),
    ),
    LockDecl(
        key="server.client_params",
        level=40,
        kind="asyncio.Lock",
        where="server/block_server.py BlockServer._client_params_lock",
        doc=(
            "single-flights the lazy multi-GB client-params load; peer "
            "of server.pruner (they never nest)"
        ),
        patterns=("_client_params_lock",),
    ),
    LockDecl(
        key="server.pruner",
        level=40,
        kind="asyncio.Lock",
        where="server/block_server.py BlockServer._pruner_lock",
        doc=(
            "single-flights the lazy pruner-checkpoint load; peer of "
            "server.client_params (they never nest)"
        ),
        patterns=("_pruner_lock",),
    ),
    LockDecl(
        key="wire.flow",
        level=45,
        kind="asyncio.Condition",
        where="wire/flow.py AdaptiveLimiter._cond",
        doc=(
            "bounds in-flight sends per connection; only bookkeeping runs "
            "under it (the slot itself is held across the send, the "
            "condition is not), so it sits just above the single-flight "
            "locks and below rpc.send"
        ),
        path_suffix="wire/flow.py",
        local_patterns=("_cond",),
    ),
    LockDecl(
        key="rpc.send",
        level=50,
        kind="asyncio.Lock",
        where="wire/rpc.py Connection._send_lock",
        doc=(
            "keeps one frame's write+drain atomic on the transport; "
            "innermost asyncio lock — nothing may be acquired under it"
        ),
        patterns=("_send_lock",),
    ),
    LockDecl(
        key="kv.cache_manager",
        level=60,
        kind="threading.RLock",
        where="kv/cache_manager.py CacheManager._lock (@_locked)",
        doc=(
            "serializes table/arena mutations across the compute thread "
            "and the event loop; reentrant because the reclaimer runs "
            "inside write paths that already hold it"
        ),
        reentrant=True,
        patterns=("manager", "cache"),
        path_suffix="kv/cache_manager.py",
        local_patterns=("self._lock", "self._cond"),
    ),
    LockDecl(
        key="kv.paged_table",
        level=70,
        kind="(declared only — no lock object)",
        where="kv/paged.py PagedKVTable",
        doc=(
            "the table deliberately carries NO lock (every mutation runs "
            "under kv.cache_manager); the level fences any future table "
            "lock BELOW the manager, matching the call direction"
        ),
        patterns=("table", "paged"),
    ),
    LockDecl(
        key="server.compute_queue",
        level=80,
        kind="(declared only — no lock object)",
        where="server/compute_queue.py ComputeQueue",
        doc=(
            "the queue is pure-asyncio today (no condition since the "
            "PR 9 hierarchy was declared); the level fences any future "
            "queue lock below the table, matching dispatch order"
        ),
        patterns=("compute", "queue"),
    ),
    LockDecl(
        key="utils.ledger",
        level=90,
        kind="threading.Lock",
        where="utils/ledger.py _lock",
        doc=(
            "guards the recovery-coverage counters; leaf — ledger points "
            "fire from arbitrary lock contexts and must never nest"
        ),
        path_suffix="utils/ledger.py",
        local_patterns=("_lock",),
    ),
    LockDecl(
        key="wire.codec_stats",
        level=90,
        kind="threading.Lock",
        where="wire/tensor_codec.py _TransportStats._lock",
        doc=(
            "guards the transport profiling counters; leaf — recorded "
            "inside (de)serialization from arbitrary lock contexts"
        ),
        path_suffix="wire/tensor_codec.py",
        local_patterns=("self._lock",),
    ),
)


def by_key() -> dict[str, LockDecl]:
    return {d.key: d for d in HIERARCHY}


def level_of(key: str) -> int | None:
    d = by_key().get(key)
    return None if d is None else d.level


def classify(text: str, path: str = "") -> str | None:
    """Map a with-context expression (lowercased, string literals already
    stripped) to a declared lock key, or None when it isn't one of ours.
    Generic `self._lock` spellings resolve by declaring module; the
    coarse manager/table/queue tokens keep PR 9's fixtures (and any
    same-shaped future code) classifying exactly as before."""
    if "lock" not in text and "cond" not in text:
        return None
    p = path.replace("\\", "/")
    # path-scoped spellings first: `self._lock` means a DIFFERENT lock
    # per module, so the global token passes must not claim those files
    for d in HIERARCHY:
        if d.path_suffix and p.endswith(d.path_suffix):
            if any(t in text for t in d.local_patterns):
                return d.key
    for d in HIERARCHY:
        if any(t in text for t in d.patterns):
            return d.key
    return None


def edge_allowed(held: str, acquired: str) -> tuple[bool, str]:
    """Is acquiring `acquired` while holding `held` consistent with the
    declared partial order? Returns (ok, reason-when-not)."""
    decls = by_key()
    a, b = decls.get(held), decls.get(acquired)
    if a is None or b is None:
        return True, ""  # unknown locks are outside the declared order
    if held == acquired:
        if a.reentrant:
            return True, ""
        return False, f"{held} is not reentrant ({a.kind})"
    if b.level > a.level:
        return True, ""
    if b.level == a.level:
        return False, (
            f"{acquired} and {held} are unordered peers (both level "
            f"{a.level}) and must never nest"
        )
    return False, (
        f"{acquired} (level {b.level}) acquired while holding {held} "
        f"(level {a.level}); the declared order is ascending"
    )


def describe() -> str:
    """The authoritative lock-hierarchy table (ARCHITECTURE.md's
    generated "Lock hierarchy" section body)."""
    lines = [
        "| level | lock | kind | declared at | protects |",
        "|---|---|---|---|---|",
    ]
    for d in HIERARCHY:
        reent = " (reentrant)" if d.reentrant else ""
        lines.append(
            f"| {d.level} | `{d.key}` | {d.kind}{reent} | {d.where} "
            f"| {d.doc} |"
        )
    return "\n".join(lines)
