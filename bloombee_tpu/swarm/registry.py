"""Registry service: the swarm's discovery plane.

Role of the reference's hivemind DHT + declare_active_modules /
get_remote_module_infos (/root/reference/src/bloombee/utils/dht.py:28-117):
servers periodically store `{uid}.{block}` -> {server_id: (info, expiry)};
records expire, and expiry IS the failure detector (a dead server's records
vanish after `expiration` seconds — reference server.py:957-992). Clients
fetch many uids at once to build the routing table.

Deployment: one `RegistryServer` runs as the bootstrap node (the reference's
`run_dht` role, cli/run_dht.py). `InProcessRegistry` backs single-process
tests. The registry speaks the normal wire RPC so any peer can also proxy it.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os

from bloombee_tpu.swarm.data import ModuleInfo, ServerInfo
from bloombee_tpu.utils import clock, lockwatch
from bloombee_tpu.wire.rpc import Connection, RpcServer, connect

logger = logging.getLogger(__name__)


class _Store:
    # deletes live this long as tombstones so a replica that missed the
    # delete can't resurrect the record in a merged read (must outlive the
    # stale record's own expiration, default 30s announces)
    TOMBSTONE_TTL = 60.0

    def __init__(self):
        # key -> subkey -> (value dict | None, expiration, stored_at)
        # value None = tombstone (deleted; newer than any older live record)
        self._data: dict[
            str, dict[str, tuple[dict | None, float, float]]
        ] = {}

    def store(
        self, key: str, subkey: str, value: dict, expiration: float,
        stored_at: float | None = None,
    ):
        # stored_at is stamped by the WRITER (server/client), not this
        # replica's clock: one actor's clock then orders its own
        # announce/revoke sequence identically on every replica, so the
        # replicated merge is immune to cross-replica clock skew
        self._data.setdefault(key, {})[subkey] = (
            value, expiration, clock.now() if stored_at is None else stored_at,
        )

    # --------------------------------------------------------- persistence
    def snapshot(self) -> list:
        """Live records (and tombstones) as a JSON-serializable list."""
        now = clock.now()
        return [
            {"key": k, "subkey": sk, "value": v, "expiration": exp,
             "stored_at": t}
            for k, sub in self._data.items()
            for sk, (v, exp, t) in sub.items()
            if exp > now
        ]

    def load_snapshot(self, records: list) -> None:
        now = clock.now()
        for r in records:
            if r["expiration"] > now:
                self._data.setdefault(r["key"], {})[r["subkey"]] = (
                    r["value"], r["expiration"],
                    r.get("stored_at", now),
                )

    def get(self, key: str) -> dict[str, tuple[dict | None, float]]:
        """subkey -> (value | None-for-tombstone, stored_at), expired pruned."""
        now = clock.now()
        out = {}
        sub = self._data.get(key)
        if not sub:
            return out
        dead = []
        for sk, (v, exp, t) in sub.items():
            if exp < now:
                dead.append(sk)
            else:
                out[sk] = (v, t)
        for sk in dead:
            del sub[sk]
        return out

    def delete(
        self, key: str, subkey: str, ttl: float | None = None,
        stored_at: float | None = None,
    ):
        now = clock.now()
        self._data.setdefault(key, {})[subkey] = (
            None,
            now + (self.TOMBSTONE_TTL if ttl is None else ttl),
            now if stored_at is None else stored_at,
        )


class RegistryServer:
    """Standalone registry node (bootstrap peer).

    `persist_path` makes the record store survive restarts: records are
    snapshotted to disk every `persist_period` seconds (and on stop) and
    reloaded at start — a restarted registry immediately knows the swarm
    instead of waiting an announce period for every server (the reference's
    DHT survives via peer replication; a single-node registry needs a disk
    snapshot instead).
    """

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        persist_path: str | None = None,
        persist_period: float = 5.0,
    ):
        self._store = _Store()
        self.persist_path = persist_path
        self.persist_period = persist_period
        self._persist_task: asyncio.Task | None = None
        # audited error swallows: persistence failures must not take down
        # the discovery plane, but they must not be silent either —
        # surfaced via rpc_info so `cli/health --probe` sees them
        self.swallowed_errors = 0
        self._swallow_logged: set[tuple[str, str]] = set()
        self.rpc = RpcServer(
            unary_handlers={
                "registry_store": self._rpc_store,
                "registry_get": self._rpc_get,
                "registry_delete": self._rpc_delete,
                "rpc_info": self._rpc_info,
            },
            host=host,
            port=port,
        )

    @property
    def port(self) -> int:
        return self.rpc.port

    def _note_swallow(self, site: str, exc: Exception) -> None:
        """Count a deliberately-survived error, warning once per
        (site, exception type) so a persistent cause logs exactly one
        line instead of one per period — or zero."""
        self.swallowed_errors += 1
        cause = (site, type(exc).__name__)
        if cause not in self._swallow_logged:
            self._swallow_logged.add(cause)
            logger.warning(
                "registry: %s failed (%s: %s) — continuing; counted in "
                "registry_swallowed_errors", site, type(exc).__name__, exc,
            )

    async def start(self):
        if self.persist_path and os.path.exists(self.persist_path):
            try:
                # read + parse off-loop: a registry restarting into a big
                # swarm snapshot must not stall peers already reconnecting
                snap = await asyncio.to_thread(self._read_snapshot)
                self._store.load_snapshot(snap)
            except Exception as e:
                # a corrupt snapshot must not block bootstrap
                self._note_swallow("snapshot load", e)
        await self.rpc.start()
        if self.persist_path:
            self._persist_task = asyncio.create_task(self._persist_loop())

    async def stop(self):
        if self._persist_task is not None:
            self._persist_task.cancel()
            try:
                # an in-flight to_thread write keeps running through
                # cancel(); await it so the final write can't race it on
                # the same .tmp file
                await self._persist_task
            except (asyncio.CancelledError, Exception):
                pass
            # final write off-loop too: stop() runs while peer
            # connections are still draining on this loop
            await asyncio.to_thread(self._write_snapshot)
        await self.rpc.stop()

    def _read_snapshot(self) -> dict:
        with open(self.persist_path) as f:
            return json.load(f)

    def _write_snapshot(self) -> None:
        tmp = f"{self.persist_path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self._store.snapshot(), f)
        os.replace(tmp, self.persist_path)

    async def _persist_loop(self) -> None:
        while True:
            await clock.async_sleep(self.persist_period)
            try:
                await asyncio.to_thread(self._write_snapshot)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # a failed periodic write must not kill the loop (the
                # next period retries), but it must be counted
                self._note_swallow("snapshot write", e)

    async def _rpc_store(self, meta: dict, tensors):
        now = clock.now()
        for rec in meta["records"]:
            self._store.store(
                rec["key"], rec["subkey"], rec["value"],
                now + rec["expiration"],
                stored_at=rec.get("stored_at"),
            )
        return {"ok": True}, []

    async def _rpc_get(self, meta: dict, tensors):
        # each record ships as {"v": value-or-None, "t": stored_at} so a
        # replicated reader can do latest-write-wins across replicas
        return {
            "results": {
                k: {
                    sk: {"v": v, "t": t}
                    for sk, (v, t) in self._store.get(k).items()
                }
                for k in meta["keys"]
            }
        }, []

    async def _rpc_info(self, meta: dict, tensors):
        """Probe endpoint (cli/health --probe reaches every advertised rpc
        server with this): registry identity + the swallowed-error audit."""
        return {
            "kind": "registry",
            "registry_swallowed_errors": self.swallowed_errors,
            "keys": len(self._store._data),
            "server_time": clock.now(),
        }, []

    async def _rpc_delete(self, meta: dict, tensors):
        for rec in meta["records"]:
            self._store.delete(
                rec["key"], rec["subkey"], ttl=rec.get("ttl"),
                stored_at=rec.get("stored_at"),
            )
        return {"ok": True}, []


class RegistryClient:
    """Client handle to the registry (used by servers and model clients)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._conn: Connection | None = None
        self._lock = lockwatch.async_lock("registry.client")

    async def _connection(self) -> Connection:
        async with self._lock:
            if self._conn is None or self._conn.is_closing():
                self._conn = await connect(self.host, self.port)
            return self._conn

    async def close(self):
        if self._conn is not None:
            await self._conn.close()
            self._conn = None

    async def declare_blocks(
        self,
        model_uid: str,
        server_id: str,
        blocks: range,
        info: ServerInfo,
        expiration: float = 30.0,
    ) -> None:
        """reference: declare_active_modules (utils/dht.py:28-73)."""
        conn = await self._connection()
        now = clock.now()
        records = [
            {
                "key": f"{model_uid}.{i}",
                "subkey": server_id,
                "value": info.to_wire(),
                "expiration": expiration,
                "stored_at": now,  # writer's clock orders announce vs revoke
            }
            for i in blocks
        ]
        await conn.call("registry_store", {"records": records})

    async def revoke_blocks(
        self, model_uid: str, server_id: str, blocks: range,
        expiration: float = 60.0,
    ) -> None:
        """`expiration` must be >= the announce expiration so the tombstone
        outlives any stale live record on a replica that missed the
        delete."""
        conn = await self._connection()
        now = clock.now()
        records = [
            {
                "key": f"{model_uid}.{i}",
                "subkey": server_id,
                "ttl": expiration,
                "stored_at": now,
            }
            for i in blocks
        ]
        await conn.call("registry_delete", {"records": records})

    async def get_records(
        self, model_uid: str, blocks: range
    ) -> list[dict[str, tuple[dict | None, float]]]:
        """Per-block raw record maps: server_id -> (wire value | None for a
        tombstone, stored_at). The replicated reader merges these by
        latest-write-wins."""
        conn = await self._connection()
        keys = [f"{model_uid}.{i}" for i in blocks]
        meta, _ = await conn.call("registry_get", {"keys": keys})
        return [
            {
                sid: (rec["v"], rec["t"])
                for sid, rec in meta["results"].get(key, {}).items()
            }
            for key in keys
        ]

    async def get_module_infos(
        self, model_uid: str, blocks: range
    ) -> list[ModuleInfo]:
        """reference: get_remote_module_infos (utils/dht.py:74-117)."""
        raw = await self.get_records(model_uid, blocks)
        return _records_to_infos(model_uid, blocks, raw)


def _records_to_infos(
    model_uid: str, blocks, raw: list[dict]
) -> list[ModuleInfo]:
    """Raw (value|tombstone, stored_at) maps -> ModuleInfo list."""
    out = []
    for i, sub in zip(blocks, raw):
        servers = {}
        for sid, (v, t) in sub.items():
            if v is None:  # drop tombstones
                continue
            info = ServerInfo.from_wire(v)
            # advert freshness for load-aware routing: stored_at is stamped
            # by the WRITER (same clock as the load snapshot's own ts), so
            # it's the staleness fallback when an advert carries a load
            # dict without a usable ts. Non-wire attribute on purpose —
            # to_wire()/asdict never re-publish it.
            info.advert_stored_at = t
            servers[sid] = info
        out.append(ModuleInfo(uid=f"{model_uid}.{i}", servers=servers))
    return out


class ReplicatedRegistry:
    """N-replica registry client: announce everywhere, read anywhere.

    The reference's hivemind DHT replicates records across peers; a single
    TCP registry is a point of failure. This client restores the
    availability story without a DHT: servers declare to EVERY replica each
    announce period, reads race all replicas and merge whatever answered by
    first-success + a short grace window (a wedged replica costs `read_grace`,
    not `timeout`), and any operation succeeds as long as ONE replica is
    reachable. Merging is latest-write-wins per (block, server) using each
    record's stored_at, and deletes are tombstones — a replica that missed a
    revoke cannot resurrect the dead server in the merged view. A replica
    that restarts repopulates from its disk snapshot plus the next announce
    wave, so no cross-registry gossip protocol is needed.
    """

    def __init__(
        self,
        replicas: list[RegistryClient],
        timeout: float = 5.0,
        read_grace: float = 0.25,
    ):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        self.timeout = timeout
        self.read_grace = read_grace

    async def _fanout(self, op_name: str, coros: list, read: bool = False):
        """Run one op against every replica; return per-replica results
        (exceptions included). Raises only if ALL replicas fail.

        Writes wait for every replica (bounded by `timeout` each — they must
        land broadly). Reads return at first success + `read_grace`: healthy
        replicas answer within the grace window; a wedged one is abandoned.
        """
        tasks = [
            asyncio.ensure_future(asyncio.wait_for(c, self.timeout))
            for c in coros
        ]
        if not read:
            results = await asyncio.gather(*tasks, return_exceptions=True)
        else:
            loop = asyncio.get_event_loop()
            deadline = loop.time() + self.timeout
            success_at = None
            pending = set(tasks)
            while pending:
                now = loop.time()
                budget = deadline - now
                if success_at is not None:
                    budget = min(budget, success_at + self.read_grace - now)
                if budget <= 0:
                    break
                done, pending = await asyncio.wait(
                    pending, timeout=budget,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if success_at is None and any(
                    not t.cancelled() and t.exception() is None for t in done
                ):
                    success_at = loop.time()
            for t in pending:
                t.cancel()
            results = [
                (TimeoutError("abandoned slow replica") if t in pending
                 else (t.exception() or t.result()))
                for t in tasks
            ]
        if all(isinstance(r, BaseException) for r in results):
            raise RuntimeError(
                f"registry {op_name} failed on all "
                f"{len(self.replicas)} replicas: {results[0]!r}"
            )
        return results

    async def declare_blocks(self, model_uid, server_id, blocks, info,
                             expiration: float = 30.0) -> None:
        await self._fanout(
            "declare",
            [
                r.declare_blocks(model_uid, server_id, blocks, info,
                                 expiration)
                for r in self.replicas
            ],
        )

    async def revoke_blocks(self, model_uid, server_id, blocks,
                            expiration: float = 60.0) -> None:
        await self._fanout(
            "revoke",
            [r.revoke_blocks(model_uid, server_id, blocks,
                             expiration=expiration)
             for r in self.replicas],
        )

    async def get_module_infos(self, model_uid, blocks) -> list[ModuleInfo]:
        results = await self._fanout(
            "get",
            [r.get_records(model_uid, blocks) for r in self.replicas],
            read=True,
        )
        blocks = list(blocks)
        # latest-write-wins per (block, server): tombstones carry stored_at
        # like live records, so the newest fact (announce vs revoke) rules
        merged: list[dict] = [{} for _ in blocks]
        for res in results:
            if isinstance(res, BaseException):
                continue
            for m, sub in zip(merged, res):
                for sid, (v, t) in sub.items():
                    if sid not in m or t > m[sid][1]:
                        m[sid] = (v, t)
        return _records_to_infos(model_uid, blocks, merged)

    async def close(self):
        await asyncio.gather(
            *(r.close() for r in self.replicas), return_exceptions=True
        )


def make_registry(spec: str, timeout: float = 5.0):
    """Build a registry client from 'host:port' or
    'host:port,host:port,...' (replicated)."""
    clients = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, port = part.rsplit(":", 1)
        clients.append(RegistryClient(host, int(port)))
    if not clients:
        raise ValueError(f"no registry addresses in {spec!r}")
    if len(clients) == 1:
        return clients[0]
    return ReplicatedRegistry(clients, timeout=timeout)


class InProcessRegistry:
    """Registry + client fused for single-process tests."""

    def __init__(self):
        self._store = _Store()

    async def declare_blocks(self, model_uid, server_id, blocks, info,
                             expiration: float = 30.0):
        now = clock.now()
        for i in blocks:
            self._store.store(
                f"{model_uid}.{i}", server_id, info.to_wire(), now + expiration
            )

    async def revoke_blocks(self, model_uid, server_id, blocks,
                            expiration: float = 60.0):
        for i in blocks:
            self._store.delete(
                f"{model_uid}.{i}", server_id, ttl=expiration
            )

    async def get_module_infos(self, model_uid, blocks):
        raw = [self._store.get(f"{model_uid}.{i}") for i in blocks]
        return _records_to_infos(model_uid, blocks, raw)

    async def close(self):
        pass
