"""Runtime lock-order witness — the dynamic half of bbtpu-lint's
concurrency story (the static half is analysis/callgraph.py + BB002/
BB003/BB009).

Static analysis proves what the code CAN do; this module records what a
run ACTUALLY did. Opt-in via ``BBTPU_LOCKWATCH=1``: the package's locks
are constructed through :func:`thread_lock` / :func:`async_lock`, which
return plain stdlib locks when the switch is off (zero overhead, zero
behavior change) and thin witness wrappers when it's on. Every wrapper
acquisition records acquisition-order edges ``(held key, acquired key)``
into one process-wide graph — per-task held-sets ride a ContextVar
(copy-on-write tuples, so they survive await boundaries and propagate
through ``asyncio.to_thread``), per-thread held-sets a threading.local —
and checks each edge against the declared partial order
(analysis/lock_hierarchy.py) as it happens.

At interpreter exit the witness appends one JSON line to
``BBTPU_LOCKWATCH_REPORT`` (append mode, multi-process merge — same
shape as utils/ledger.py). ``python -m bloombee_tpu.utils.lockwatch PATH
--require`` merges the lines, runs cycle detection over the union edge
graph, and fails (exit 1) when the run observed ZERO cross-lock edges —
a witness that watched nothing is a vacuous green, exactly like an
empty chaos ledger — or when ANY hierarchy violation or cycle was
observed. An observed edge the declared order calls impossible is the
cross-validation failing: either the code or the declaration is wrong,
and both are one file away.

Scope: the package's Locks (thread and asyncio). Conditions
(wire/flow.py limiter, cache_manager admission) stay unwatched — their
critical sections are pure bookkeeping and wrapping wait/notify adds
witness states the graph can't interpret. clock is deliberately NOT
imported here (the ledger/clock/lockwatch utility layer must stay
import-cycle-free).
"""

from __future__ import annotations

import atexit
import contextvars
import json
import threading

from bloombee_tpu.analysis import lock_hierarchy
from bloombee_tpu.utils import env

env.declare(
    "BBTPU_LOCKWATCH", bool, False,
    "wrap the package's locks in runtime lock-order witnesses: records "
    "per-thread/per-task acquisition-order edges, validates them against "
    "the declared hierarchy (analysis/lock_hierarchy.py) live, and "
    "reports at exit. Off = plain stdlib locks, zero overhead",
)
env.declare(
    "BBTPU_LOCKWATCH_REPORT", str, "",
    "path to append this process's lock-witness report to at exit (one "
    "JSON line: observed edges, hierarchy violations); empty = in-memory "
    "only. Set by scripts/chaos.sh so the gate can cross-validate the "
    "run against the static lock model",
)

_MAX_VIOLATIONS = 100  # keep the report bounded under a hot violation


class _Witness:
    """Process-wide acquisition-order graph. Internal mutex is a PLAIN
    threading.Lock — the witness must never watch itself."""

    def __init__(self):
        self._mu = threading.Lock()
        self.edges: dict[tuple[str, str], int] = {}
        self.violations: list[dict] = []
        self._tls = threading.local()
        self._task_held: contextvars.ContextVar[tuple[str, ...]] = (
            contextvars.ContextVar("bbtpu_lockwatch_held", default=())
        )

    # ------------------------------------------------------------- stacks
    def _thread_stack(self) -> list[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held(self) -> tuple[str, ...]:
        """Everything this execution context holds: the task's asyncio
        holds (visible to sync code running inline on the loop, and to
        to_thread workers via context propagation) plus this thread's
        thread-lock holds."""
        return self._task_held.get() + tuple(self._thread_stack())

    # ------------------------------------------------------------ recording
    def acquire(self, key: str, reentrant: bool, domain: str) -> None:
        held = self.held()
        with self._mu:
            for h in held:
                if h == key:
                    if not reentrant:
                        self._violation(
                            h, key, f"{key} re-acquired (not reentrant)"
                        )
                    continue
                pair = (h, key)
                self.edges[pair] = self.edges.get(pair, 0) + 1
                ok, why = lock_hierarchy.edge_allowed(h, key)
                if not ok:
                    self._violation(h, key, why)
        if domain == "task":
            self._task_held.set(self._task_held.get() + (key,))
        else:
            self._thread_stack().append(key)

    def release(self, key: str, domain: str) -> None:
        if domain == "task":
            held = list(self._task_held.get())
            if key in held:
                held.reverse()
                held.remove(key)
                held.reverse()
                self._task_held.set(tuple(held))
        else:
            st = self._thread_stack()
            if key in st:
                st.reverse()
                st.remove(key)
                st.reverse()

    def _violation(self, held: str, acquired: str, why: str) -> None:
        if len(self.violations) < _MAX_VIOLATIONS:
            self.violations.append(
                {"held": held, "acquired": acquired, "why": why}
            )

    # ------------------------------------------------------------- reading
    def snapshot(self) -> dict:
        with self._mu:
            return {
                "edges": [
                    [a, b, n] for (a, b), n in sorted(self.edges.items())
                ],
                "violations": list(self.violations),
            }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.violations.clear()
        # also drop the CALLING context's held-state (other threads'
        # stacks are theirs to unwind): a harness that leaked a hold
        # would otherwise poison every later record with false edges
        self._thread_stack().clear()
        self._task_held.set(())


_witness = _Witness()
_atexit_registered = False


def enabled() -> bool:
    return bool(env.get("BBTPU_LOCKWATCH"))


def _ensure_atexit() -> None:
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        if env.get("BBTPU_LOCKWATCH_REPORT"):
            atexit.register(flush)


# ------------------------------------------------------------ lock wrappers
class _WatchedThreadLock:
    def __init__(self, key: str, reentrant: bool):
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._key = key
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _witness.acquire(self._key, self._reentrant, "thread")
        return ok

    def release(self) -> None:
        _witness.release(self._key, "thread")
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _WatchedAsyncLock:
    def __init__(self, key: str):
        import asyncio

        self._inner = asyncio.Lock()
        self._key = key

    async def acquire(self) -> bool:
        await self._inner.acquire()
        _witness.acquire(self._key, False, "task")
        return True

    def release(self) -> None:
        _witness.release(self._key, "task")
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    async def __aenter__(self):
        await self.acquire()
        return self

    async def __aexit__(self, *exc) -> None:
        self.release()


def thread_lock(key: str, reentrant: bool = False):
    """A threading.Lock/RLock for hierarchy key `key` — plain stdlib
    object when the witness is off (the zero-overhead contract)."""
    if not enabled():
        return threading.RLock() if reentrant else threading.Lock()
    _ensure_atexit()
    return _WatchedThreadLock(key, reentrant)


def async_lock(key: str):
    """An asyncio.Lock for hierarchy key `key` — plain asyncio.Lock when
    the witness is off. Construct on the loop, like asyncio.Lock."""
    if not enabled():
        import asyncio

        return asyncio.Lock()
    _ensure_atexit()
    return _WatchedAsyncLock(key)


# --------------------------------------------------------------- reporting
def find_cycles(edges) -> list[list[str]]:
    """Cycles in an edge iterable of (a, b) pairs — impossible while
    every edge respects the ascending declared order, so any cycle means
    undeclared locks interleaving in both directions."""
    adj: dict[str, set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    cycles: list[list[str]] = []
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {k: WHITE for k in adj}
    path: list[str] = []

    def dfs(u: str) -> None:
        color[u] = GRAY
        path.append(u)
        for v in adj.get(u, ()):
            c = color.get(v, WHITE)
            if c == GRAY:
                cycles.append(path[path.index(v):] + [v])
            elif c == WHITE:
                dfs(v)
        path.pop()
        color[u] = BLACK

    for k in list(adj):
        if color.get(k, WHITE) == WHITE:
            dfs(k)
    return cycles


def counters() -> dict:
    """Live counter pair for rpc_info / health --probe."""
    snap = _witness.snapshot()
    return {
        "lock_order_edges": len(snap["edges"]),
        "lock_violations": (
            len(snap["violations"])
            + len(find_cycles((a, b) for a, b, _ in snap["edges"]))
        ),
    }


def snapshot() -> dict:
    return _witness.snapshot()


def reset() -> None:
    _witness.reset()


def flush(path: str | None = None) -> None:
    """Append this process's witness report as one JSON line (atexit
    hook; callable directly by harnesses)."""
    path = path or env.get("BBTPU_LOCKWATCH_REPORT")
    if not path:
        return
    snap = _witness.snapshot()
    if not snap["edges"] and not snap["violations"]:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(snap, sort_keys=True) + "\n")
    except OSError:  # the witness must never take down the run it audits
        pass


def merge_lines(text: str) -> dict:
    """Merge a multi-process report file into one edge/violation set."""
    edges: dict[tuple[str, str], int] = {}
    violations: list[dict] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            snap = json.loads(line)
        except ValueError:
            continue
        for a, b, n in snap.get("edges") or []:
            edges[(a, b)] = edges.get((a, b), 0) + int(n)
        violations.extend(snap.get("violations") or [])
    return {
        "edges": [[a, b, n] for (a, b), n in sorted(edges.items())],
        "violations": violations,
    }


def _main(argv=None) -> int:
    """``python -m bloombee_tpu.utils.lockwatch PATH [--require]``: merge
    and print a witness report; with --require, exit 1 unless the run
    observed >=1 cross-lock edge (proof the witness wasn't vacuous) with
    ZERO hierarchy violations and ZERO cycles."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(description=_main.__doc__)
    ap.add_argument("path")
    ap.add_argument("--require", action="store_true",
                    help="fail (exit 1) on zero edges or any violation")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            text = f.read()
    except OSError:
        text = ""
    merged = merge_lines(text)
    cycles = find_cycles((a, b) for a, b, _ in merged["edges"])
    print(
        f"lockwatch: {len(merged['edges'])} edge(s), "
        f"{len(merged['violations'])} violation(s), "
        f"{len(cycles)} cycle(s)"
    )
    for a, b, n in merged["edges"]:
        print(f"  edge {a} -> {b} x{n}")
    for v in merged["violations"]:
        print(f"  VIOLATION {v['held']} -> {v['acquired']}: {v['why']}")
    for c in cycles:
        print(f"  CYCLE {' -> '.join(c)}")
    if args.require:
        if not merged["edges"]:
            print(
                "lockwatch: EMPTY — a witness-enabled run must observe "
                ">=1 cross-lock acquisition edge; a run that never nested "
                "two watched locks validated nothing", file=sys.stderr,
            )
            return 1
        if merged["violations"] or cycles:
            print(
                "lockwatch: observed lock order contradicts the declared "
                "hierarchy (analysis/lock_hierarchy.py) — either the code "
                "or the declaration is wrong; fix one", file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
