"""Swarm-scale traffic simulator on the virtual clock.

Runs the REAL control plane — ComputeQueue scheduling and group
coalescing, AdmissionController fair-share shedding, the standby
promotion/demotion state machine (server/promotion.py mixin), measured
rebalancing (server/block_selection.rebalance_if_needed), and client-side
Dijkstra routing with ban/quarantine/overload penalty classes
(client/sequence_manager.py) — against thousands of virtual sessions on a
``SteppableClock``, with device compute replaced by a calibrated cost
model. Only the two leaves are simulated: the matmul (a ``clock.sleep``
of the modeled cost on the compute thread) and the wire (a virtual RTT).
Everything between — every watermark, dwell window, backoff, and
hysteresis margin — is byte-for-byte the code production runs.

The point is the failure modes that only appear at swarm scale:
metastable shed/retry feedback loops after a flash crowd, promotion
storms and flapping under span loss, rebalance thrash on diurnal ramps,
and retry amplification past the point of no return.  ``python -m
bloombee_tpu.sim --require`` runs the scenario suite and FAILS (exit 3)
on metastable outcomes, the same gate idiom as utils/ledger.py and
utils/lockwatch.py.

Layout:
  engine.py    discrete-event conductor over SteppableClock + counting
               executor (knows when real compute threads are mid-flight)
  cost.py      calibrated per-dispatch cost model (fit from BENCH JSON)
  node.py      SimServer: real queue/admission/promotion/rebalance
  client.py    virtual sessions driving real RemoteSequenceManager routes
  workload.py  generative arrivals: heavy tails, diurnal ramps, agent
               loops with shared prefixes, flash crowds
  scenarios.py swarm topologies + fault scripts (wire/faults.py schedules)
  metrics.py   per-scenario JSON metrics + metastability gates
"""

from bloombee_tpu.sim.cost import CostModel
from bloombee_tpu.sim.engine import SimEngine, SimStalled
from bloombee_tpu.sim.scenarios import SCENARIOS, run_scenario

__all__ = [
    "CostModel",
    "SimEngine",
    "SimStalled",
    "SCENARIOS",
    "run_scenario",
]
