#!/usr/bin/env bash
# Chaos gate: replay the chaos-marked suite under a fixed seed matrix of
# ambient wire faults (the BBTPU_CHAOS_* env plan). Each matrix entry is a
# space-separated list of KEY=VAL tokens; anything unset takes the default
# below, so entries name ONLY what they vary (the old positional
# "SEED:DELAY_P:ADMIT:..." strings needed every column on every entry and
# silently misassigned values when a column was added).
#
# Keys:
#   SEED         chaos RNG seed (replays are bit-for-bit per seed)
#   DELAY_P      per-frame send-delay probability (mild ambient jitter, so
#                the per-test seeded FaultPlans stay the dominant source)
#   ADMIT        1 = server admission control (BBTPU_ADMIT, low watermark)
#                so overload shed-and-reroute runs under the same jitter
#   PARTITION_P  silent both-way blackhole probability (no FIN/RST);
#                keepalive is forced small so half-open detection + lease
#                park/resume are the recovery under test
#   MIXED        1 = mixed-batch dispatch (BBTPU_MIXED_BATCH)
#   SPEC         1 = batched tree-speculative verification (BBTPU_SPEC_BATCH)
#   REBALANCE    1 = elastic control loop (measured-load rebalance + fast
#                promotion watermarks)
#   CORRUPT      per-frame probability of corrupting a span-output reply
#                tensor in-flight (well-formed frame, wrong numbers).
#                Forces BBTPU_INTEGRITY=1: only the client integrity layer
#                (out_digest + sanity gate) can see this fault class, and
#                the suite must stay green + token-identical through it
#   LOCKWATCH    1 = runtime lock-order witness (BBTPU_LOCKWATCH): every
#                package lock records acquisition-order edges, checked
#                against the declared hierarchy. Gated ledger-style: the
#                entry must observe >=1 cross-lock edge (a witness that
#                watched nothing proved nothing) with zero violations
#                and zero cycles
#   JITWATCH     1 = runtime compile/transfer witness (BBTPU_JITWATCH):
#                every XLA backend compile is ledgered with its
#                (function, shape bucket, phase) attribution. Gated the
#                same no-vacuous-green way: the entry must observe >=1
#                warmup compile behind a dropped warmup fence and ZERO
#                steady-state recompiles (a decode bucket that escaped
#                BlockServer.warmup is a first-token compile stall some
#                session actually paid)
#   ARTIFACT     1 = compile-artifact cache entry: strengthens both gates.
#                The ledger gate additionally requires the
#                server.artifact_fallback_compile recovery point (the
#                corrupt/declined-artifact fallback path must actually
#                run), and the jitwatch gate runs in --preinstalled mode
#                (a pre-installed standby must warm up entirely from
#                persistent-cache hits — any real warmup compile for a
#                pre-installed bucket is a red)
#   UNIRAGGED    1 = universal ragged dispatch forced end to end: derives
#                MIXED=1 SPEC=1 so decode rows, tree-verify rows, and
#                prefill chunks all funnel through the ONE kind-aware
#                gather + ragged_group device step while the entry's
#                jitwatch gate proves the unified buckets pre-compiled
#                (zero steady-state recompiles) and the ledger gate
#                proves per-kind rollback machinery actually ran
#   CODEC        1 = streaming wire-path entry: forces every frame through
#                the off-loop codec pipeline (BBTPU_WIRE_PIPELINE=1 with
#                inline threshold 0, so no frame takes the small-payload
#                fast path) while DELAY + CORRUPT land on pipelined
#                frames; the test's own plan adds seeded reset + in-flight
#                corruption with the integrity layer on, so the ledger
#                gate proves decode survived the codec pool under faults
#   SIM          1 = swarm-simulator entry: replay the virtual-clock
#                traffic simulator's scenario sweep (`python -m
#                bloombee_tpu.sim --require --smoke`) INSTEAD of a pytest
#                leg, appending to the SAME per-entry ledger, so the
#                metastable-convergence gates (shed settle, retry
#                amplification, promotion latency, starvation) block the
#                chaos gate and the ledger proves the scripted crashes,
#                promotions, and rebalances actually ran. Runs with stock
#                tuning (its gates define healthy for the DEFAULT knobs),
#                not the entry's chaos env
#   TESTS        comma-separated test-file list for this entry (default:
#                the whole chaos-marked suite). Feature entries target the
#                files that actually exercise their flags — the per-entry
#                recovery-coverage ledger proves each one still injected
#                faults AND ran recovery machinery (no vacuous greens),
#                while the broad first entry keeps whole-suite ambient
#                coverage. Replaying all ~22 chaos tests five times bought
#                nothing the ledger can't prove more cheaply
# Fixed seeds keep every run replayable bit-for-bit (wire/faults.py
# contract).
# Exits 0 when pytest is unavailable (mirrors scripts/lint.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

if ! python -c "import pytest" >/dev/null 2>&1; then
    echo "chaos: pytest not installed; skipping" >&2
    exit 0
fi

# Each entry replays the whole chaos-marked suite (~50s), so the matrix
# is budgeted: independent feature flags share an entry instead of each
# getting their own, keeping the tier-1 gate inside its wall-clock cap
# while every flag still runs under ambient chaos.
# Persistent XLA compilation cache shared by the matrix entries: every
# entry replays the same tiny-model shapes in a fresh python process, and
# recompiling them once per entry dominated the gate's wall clock.
# Entries 2..N hit entry 1's cache instead. Correctness-neutral (XLA keys
# on HLO + compile options) and deliberately NOT part of the printed
# reproduction line — it is a perf knob, not part of the failure recipe.
compile_cache="$(mktemp -d "${TMPDIR:-/tmp}/bbtpu-chaos-xla.XXXXXX")"
trap 'rm -rf "${compile_cache}"' EXIT

# Entries that replayed the SAME files under compatible flags are merged
# (each pytest process costs ~10s of interpreter+jax startup on top of
# its tests, and the tier-1 wall cap is the scarce resource):
#   - the old standalone CORRUPT entry's test list was identical to the
#     lock-witness entry's, so corruption+integrity now ride there
#   - the old MIXED=1 SPEC=1 and JITWATCH smoke entries were subsets of
#     the universal-ragged entry's files+flags (UNIRAGGED derives both
#     fusion flags and already carries the compile witness), so their
#     files fold in and replay under the fused path
MATRIX=(
    "SEED=23 DELAY_P=0.1"
    "SEED=43 DELAY_P=0.02 PARTITION_P=0.02 CORRUPT=0.05 LOCKWATCH=1 TESTS=tests/test_session_lease.py,tests/test_chaos.py,tests/test_kv_replication.py"
    "SEED=83 DELAY_P=0.05 ADMIT=1 REBALANCE=1 TESTS=tests/test_chaos.py,tests/test_promotion.py,tests/test_kv_replication.py,tests/test_prefix_cache.py"
    "SEED=71 DELAY_P=0.02 ARTIFACT=1 JITWATCH=1 TESTS=tests/test_artifact_cache.py"
    "SEED=67 DELAY_P=0.02 UNIRAGGED=1 JITWATCH=1 TESTS=tests/test_universal_ragged.py,tests/test_mixed_batch.py,tests/test_spec_decode.py,tests/test_batched_decode.py,tests/test_chunked_prefill.py,tests/test_jitwatch.py,tests/test_chaos.py"
    "SEED=41 DELAY_P=0.05 CORRUPT=0.05 CODEC=1 TESTS=tests/test_wire_pipeline.py"
    "SEED=29 SIM=1"
)
for entry in "${MATRIX[@]}"; do
    # per-entry defaults; each entry overrides only what it varies
    SEED=0 DELAY_P=0 ADMIT=0 PARTITION_P=0 MIXED=0 SPEC=0 REBALANCE=0
    CORRUPT=0 LOCKWATCH=0 JITWATCH=0 ARTIFACT=0 UNIRAGGED=0 CODEC=0
    SIM=0
    TESTS=tests/
    for tok in ${entry}; do
        case "${tok%%=*}" in
            SEED|DELAY_P|ADMIT|PARTITION_P|MIXED|SPEC|REBALANCE|CORRUPT|LOCKWATCH|JITWATCH|ARTIFACT|UNIRAGGED|CODEC|SIM|TESTS)
                declare "${tok}" ;;
            *)
                echo "chaos: unknown matrix token '${tok}'" >&2
                exit 1 ;;
        esac
    done
    # partitioned conns go silent instead of erroring: a small keepalive
    # turns the blackhole into a prompt local abort so lease park/resume
    # (not a step_timeout expiry) is the recovery path under test
    keepalive_s=0
    if [ "${PARTITION_P}" != "0" ]; then
        keepalive_s=0.5
    fi
    # the rebalance entry runs with hair-trigger promotion watermarks so
    # the standby control loop actually fires inside short chaos tests
    promote_high_ms=1500
    promote_sustain_s=10
    if [ "${REBALANCE}" != "0" ]; then
        promote_high_ms=500
        promote_sustain_s=0.3
    fi
    # the universal-ragged entry forces BOTH fusion flags: UNIRAGGED is
    # the one-dispatch path and only exists when decode + tree + chunk
    # rows may share a gather
    if [ "${UNIRAGGED}" != "0" ]; then
        MIXED=1
        SPEC=1
    fi
    # in-flight corruption is invisible to the transport; the integrity
    # layer (server digest stamps + client gate) must be on to catch it
    integrity=0
    if [ "${CORRUPT}" != "0" ]; then
        integrity=1
    fi
    # the codec entry drops the inline threshold to 0 so even tiny decode
    # frames take the off-loop pool — the ordered-drain/backpressure path
    # under test, not the inline fast path
    wire_inline=4096
    if [ "${CODEC}" != "0" ]; then
        wire_inline=0
    fi
    # the full derived environment in one line: the run below uses it, and
    # a red entry reprints it verbatim so "reproduce this failure" is a
    # single copy-paste (matrix tokens alone hide the derived knobs)
    env_line="JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} \
BBTPU_CHAOS=1 \
BBTPU_CHAOS_SEED=${SEED} \
BBTPU_CHAOS_DELAY_P=${DELAY_P} \
BBTPU_CHAOS_DELAY_S=0.02 \
BBTPU_CHAOS_PARTITION_P=${PARTITION_P} \
BBTPU_CHAOS_CORRUPT_P=${CORRUPT} \
BBTPU_INTEGRITY=${integrity} \
BBTPU_KEEPALIVE_S=${keepalive_s} \
BBTPU_ADMIT=${ADMIT} \
BBTPU_ADMIT_HIGH_MS=400 \
BBTPU_MIXED_BATCH=${MIXED} \
BBTPU_SPEC_BATCH=${SPEC} \
BBTPU_MEASURED_REBALANCE=${REBALANCE} \
BBTPU_PROMOTE_HIGH_MS=${promote_high_ms} \
BBTPU_PROMOTE_SUSTAIN_S=${promote_sustain_s} \
BBTPU_LOCKWATCH=${LOCKWATCH} \
BBTPU_JITWATCH=${JITWATCH} \
BBTPU_WIRE_PIPELINE=1 \
BBTPU_WIRE_PIPELINE_INLINE=${wire_inline}"
    # recovery-coverage ledger: every in-process fault/recovery point
    # appends here at interpreter exit; an entry that tested nothing
    # (zero faults or zero recoveries) fails the gate even if pytest
    # went green — a vacuous pass is a gate bug, not a pass
    ledger_file="$(mktemp "${TMPDIR:-/tmp}/bbtpu-chaos-ledger.XXXXXX")"
    # lock-witness report, same multi-process append contract as the
    # ledger; gated below with the same no-vacuous-green rule
    lockwatch_file="$(mktemp "${TMPDIR:-/tmp}/bbtpu-chaos-lockwatch.XXXXXX")"
    # compile-witness report (BBTPU_JITWATCH entries), same contract
    jitwatch_file="$(mktemp "${TMPDIR:-/tmp}/bbtpu-chaos-jitwatch.XXXXXX")"
    echo "chaos: ${entry}" >&2
    entry_start=${SECONDS}
    rc=0
    test_targets="${TESTS//,/ }"
    if [ "${SIM}" != "0" ]; then
        # the SIM entry replays the simulator's own CI gate instead of a
        # pytest leg (tier-1 already runs tests/test_sim.py; replaying it
        # here would double-pay its wall cost for zero new coverage).
        # Stock tuning on purpose: the --require gates define "healthy"
        # for the DEFAULT knobs, so the chaos env would make a red
        # un-attributable. Same ledger file so the vacuity gate below
        # sees the sim's scripted crashes/promotions/rebalances
        env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
            BBTPU_CHAOS_LEDGER="${ledger_file}" \
            BBTPU_SIM_SEED="${SEED}" \
            python -m bloombee_tpu.sim --require --smoke >&2 || rc=$?
    else
        env ${env_line} BBTPU_CHAOS_LEDGER="${ledger_file}" \
            BBTPU_LOCKWATCH_REPORT="${lockwatch_file}" \
            BBTPU_JITWATCH_REPORT="${jitwatch_file}" \
            JAX_COMPILATION_CACHE_DIR="${compile_cache}" \
            JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0.5 \
            python -m pytest ${test_targets} -q -m chaos \
            -p no:cacheprovider -p no:xdist -p no:randomly "$@" || rc=$?
    fi
    # the ARTIFACT entry pins both gates to the artifact paths it exists
    # to exercise: the corrupt/declined fallback must have LEDGERED, and
    # the pre-installed standby must have warmed up from cache hits alone
    artifact_ledger_args=""
    artifact_jitwatch_args=""
    if [ "${ARTIFACT}" != "0" ]; then
        artifact_ledger_args="--require-recovery \
server.artifact_fallback_compile"
        artifact_jitwatch_args="--preinstalled"
    fi
    if [ "${rc}" -eq 0 ]; then
        python -m bloombee_tpu.utils.ledger "${ledger_file}" --require \
            ${artifact_ledger_args} >&2 || rc=$?
    fi
    if [ "${rc}" -eq 0 ] && [ "${LOCKWATCH}" != "0" ]; then
        python -m bloombee_tpu.utils.lockwatch "${lockwatch_file}" \
            --require >&2 || rc=$?
    fi
    if [ "${rc}" -eq 0 ] && [ "${JITWATCH}" != "0" ]; then
        python -m bloombee_tpu.utils.jitwatch "${jitwatch_file}" \
            --require ${artifact_jitwatch_args} >&2 || rc=$?
    fi
    elapsed=$(( SECONDS - entry_start ))
    if [ "${rc}" -ne 0 ]; then
        echo "chaos: RED entry '${entry}' after ${elapsed}s" >&2
        echo "chaos: reproduce with:" >&2
        if [ "${SIM}" != "0" ]; then
            echo "  BBTPU_SIM_SEED=${SEED}" \
                 "python -m bloombee_tpu.sim --require --smoke" >&2
        else
            echo "  ${env_line} python -m pytest ${test_targets} -q -m chaos" \
                 "-p no:cacheprovider -p no:xdist -p no:randomly" >&2
        fi
        rm -f "${ledger_file}" "${lockwatch_file}" "${jitwatch_file}"
        exit "${rc}"
    fi
    echo "chaos: entry '${entry}' green in ${elapsed}s" >&2
    rm -f "${ledger_file}" "${lockwatch_file}" "${jitwatch_file}"
done
