"""Paged decode attention kernel vs dense reference (interpreter mode)."""

import numpy as np
import pytest

import jax.numpy as jnp

from bloombee_tpu.ops.pallas.paged_attention import paged_decode_attention


def dense_reference(q, k_slab, v_slab, page_table, lens, page_size, window=0):
    """Gather pages then masked softmax — the exact dense-path semantics
    (incl. attend_paged's sliding window: key visible iff pos > q_pos - w)."""
    b, h, hd = q.shape
    hkv = k_slab.shape[1]
    g = h // hkv
    outs = []
    for i in range(b):
        slots = [
            p * page_size + o
            for p in page_table[i]
            for o in range(page_size)
        ]
        k = k_slab[np.asarray(slots)]  # [S, Hkv, hd]
        v = v_slab[np.asarray(slots)]
        s = k.shape[0]
        mask = np.arange(s) < lens[i]
        if window > 0:
            mask &= np.arange(s) > (lens[i] - 1) - window
        row = []
        for head in range(h):
            kv = head // g
            logits = (q[i, head].astype(np.float32) @
                      k[:, kv].astype(np.float32).T) * hd**-0.5
            logits = np.where(mask, logits, -1e30)
            p_att = np.exp(logits - logits.max())
            p_att = p_att / p_att.sum()
            row.append(p_att @ v[:, kv].astype(np.float32))
        outs.append(np.stack(row))
    return np.stack(outs)


@pytest.mark.parametrize("hkv,h", [(2, 8), (4, 4), (1, 6)])
def test_paged_decode_matches_dense(hkv, h):
    rng = np.random.default_rng(0)
    b, hd, page_size, n_phys, n_pages = 3, 64, 16, 12, 4
    q = rng.standard_normal((b, h, hd)).astype(np.float32)
    k_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    v_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    # shuffled physical pages; per-seq lens not page-aligned
    page_table = np.array(
        [[7, 2, 9, 0], [1, 4, 0, 0], [11, 3, 5, 8]], np.int32
    )
    lens = np.array([55, 17, 64], np.int32)

    got = np.asarray(
        paged_decode_attention(
            jnp.asarray(q), jnp.asarray(k_slab), jnp.asarray(v_slab),
            jnp.asarray(page_table), jnp.asarray(lens),
            page_size=page_size, interpret=True,
        )
    )
    want = dense_reference(q, k_slab, v_slab, page_table, lens, page_size)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [5, 16, 40])
def test_paged_decode_sliding_window(window):
    """Sliding window masks to [len-w, len) and must match attend_paged's
    semantics; pages wholly below the window are skipped in-kernel."""
    rng = np.random.default_rng(3)
    b, h, hkv, hd, page_size = 2, 4, 2, 64, 16
    n_phys = 10
    q = rng.standard_normal((b, h, hd)).astype(np.float32)
    k_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    v_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    page_table = np.array([[7, 2, 9, 0], [1, 4, 3, 6]], np.int32)
    lens = np.array([55, 33], np.int32)

    got = np.asarray(
        paged_decode_attention(
            jnp.asarray(q), jnp.asarray(k_slab), jnp.asarray(v_slab),
            jnp.asarray(page_table), jnp.asarray(lens),
            page_size=page_size, interpret=True, window=window,
        )
    )
    want = dense_reference(
        q, k_slab, v_slab, page_table, lens, page_size, window=window
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_paged_decode_bf16_and_padding_rows():
    """bf16 inputs and zero-length padding rows (executor pads B to a
    bucket): padding rows emit finite garbage that the caller drops."""
    rng = np.random.default_rng(1)
    b, h, hkv, hd, page_size = 4, 8, 2, 64, 16
    n_phys, n_pages = 8, 2
    q = rng.standard_normal((b, h, hd)).astype(np.float32)
    k_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    v_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    page_table = np.array(
        [[3, 1], [0, 2], [5, 0], [0, 0]], np.int32
    )
    lens = np.array([20, 9, 32, 0], np.int32)  # row 3 = padding

    got = np.asarray(
        paged_decode_attention(
            jnp.asarray(q, jnp.bfloat16),
            jnp.asarray(k_slab, jnp.bfloat16),
            jnp.asarray(v_slab, jnp.bfloat16),
            jnp.asarray(page_table), jnp.asarray(lens),
            page_size=page_size, interpret=True,
        ).astype(jnp.float32)
    )
    assert np.isfinite(got).all()
    want = dense_reference(
        q[:3].astype(np.float32), k_slab, v_slab, page_table[:3], lens[:3],
        page_size,
    )
    np.testing.assert_allclose(got[:3], want, rtol=2e-2, atol=2e-2)


def test_span_decode_paged_kernel_matches_dense():
    """The serving span step with the paged decode kernel on vs off
    (executor eligibility end-to-end): identical decode outputs."""
    import asyncio
    import os

    import jax
    import jax.numpy as jnp

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=64,
        num_hidden_layers=2, vocab_size=64,
    )
    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.float32)
         for i in range(2)]
    )
    prefill = np.asarray(
        jax.random.normal(jax.random.PRNGKey(7), (2, 21, 64), jnp.float32)
    ) * 0.1
    steps = [
        np.asarray(
            jax.random.normal(jax.random.PRNGKey(50 + i), (2, 1, 64))
        ) * 0.1
        for i in range(3)
    ]

    async def run_one(paged: bool):
        os.environ["BBTPU_PAGED_ATTENTION"] = "1" if paged else "0"
        os.environ["BBTPU_PAGED_INTERPRET"] = "1"
        # tiny test contexts sit below the production paged/dense
        # crossover threshold; force the kernel on
        os.environ["BBTPU_PAGED_MIN_CONTEXT"] = "0"
        try:
            manager = CacheManager(
                num_layers=2, num_pages=16, page_size=16,
                n_kv_heads=2, head_dim=64, dtype=jnp.float32,
            )
            ex = SpanExecutor(params, spec, manager,
                              compute_dtype=jnp.float32)
            async with manager.allocate(2, 64) as handle:
                outs = [ex.prefill(handle, prefill)]
                for s in steps:
                    outs.append(ex.decode(handle, s))
                return outs
        finally:
            del os.environ["BBTPU_PAGED_ATTENTION"]
            del os.environ["BBTPU_PAGED_INTERPRET"]
            del os.environ["BBTPU_PAGED_MIN_CONTEXT"]

    outs_paged = asyncio.run(run_one(True))
    outs_dense = asyncio.run(run_one(False))
    for got, want in zip(outs_paged, outs_dense):
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_span_decode_paged_kernel_sliding_windows():
    """Mistral/gemma-style alternating sliding-window layers run through
    the paged kernel (the per-layer window rides the scan) and match the
    dense path exactly."""
    import asyncio
    import os

    import jax
    import jax.numpy as jnp

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=64,
        num_hidden_layers=2, vocab_size=64,
        layer_types=("sliding", "full"), sliding_window=7,
    )
    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.float32)
         for i in range(2)]
    )
    prefill = np.asarray(
        jax.random.normal(jax.random.PRNGKey(8), (2, 19, 64), jnp.float32)
    ) * 0.1
    steps = [
        np.asarray(
            jax.random.normal(jax.random.PRNGKey(80 + i), (2, 1, 64))
        ) * 0.1
        for i in range(2)
    ]

    async def run_one(paged: bool):
        os.environ["BBTPU_PAGED_ATTENTION"] = "1" if paged else "0"
        os.environ["BBTPU_PAGED_INTERPRET"] = "1"
        # tiny test contexts sit below the production paged/dense
        # crossover threshold; force the kernel on
        os.environ["BBTPU_PAGED_MIN_CONTEXT"] = "0"
        try:
            manager = CacheManager(
                num_layers=2, num_pages=16, page_size=16,
                n_kv_heads=2, head_dim=64, dtype=jnp.float32,
            )
            ex = SpanExecutor(params, spec, manager,
                              compute_dtype=jnp.float32)
            assert ex.windows == (7, 0)
            async with manager.allocate(2, 64) as handle:
                outs = [ex.prefill(handle, prefill)]
                for s in steps:
                    outs.append(ex.decode(handle, s))
                return outs
        finally:
            del os.environ["BBTPU_PAGED_ATTENTION"]
            del os.environ["BBTPU_PAGED_INTERPRET"]
            del os.environ["BBTPU_PAGED_MIN_CONTEXT"]

    outs_paged = asyncio.run(run_one(True))
    outs_dense = asyncio.run(run_one(False))
    for got, want in zip(outs_paged, outs_dense):
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_paged_kernel_context_threshold():
    """The executor engages the paged kernel only at/above
    BBTPU_PAGED_MIN_CONTEXT (measured dense/paged crossover): long-context
    decode calls it, short-context decode stays dense."""
    import asyncio
    import os

    import jax
    import jax.numpy as jnp

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.ops.pallas import paged_attention as pk
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=64,
        num_hidden_layers=2, vocab_size=64,
    )
    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.float32)
         for i in range(2)]
    )
    rng = np.random.default_rng(0)
    calls = []
    orig = pk.paged_decode_attention

    def spy(*a, **k):
        calls.append(True)
        return orig(*a, **k)

    async def run(ctx):
        manager = CacheManager(
            num_layers=2, num_pages=80, page_size=16,
            n_kv_heads=2, head_dim=64, dtype=jnp.float32,
        )
        ex = SpanExecutor(params, spec, manager, compute_dtype=jnp.float32,
                          max_chunk_tokens=512)
        async with manager.allocate(1, ctx + 4) as handle:
            h = (rng.standard_normal((1, ctx, 64)) * 0.1).astype(np.float32)
            ex.prefill(handle, h)
            step = (rng.standard_normal((1, 1, 64)) * 0.1).astype(np.float32)
            ex.decode(handle, step)

    os.environ["BBTPU_PAGED_INTERPRET"] = "1"  # CPU backend
    pk.paged_decode_attention = spy
    try:
        # default threshold is 512: a 600-token context buckets above it
        asyncio.run(run(600))
        assert calls, "kernel not engaged at long context"
        calls.clear()
        asyncio.run(run(24))  # buckets to 64 tokens, below 512
        assert not calls, "kernel engaged below the crossover threshold"
    finally:
        pk.paged_decode_attention = orig
        del os.environ["BBTPU_PAGED_INTERPRET"]


def test_int4_paged_kernel_matches_dequantized_reference():
    """paged_decode_attention_int4 dequantizes in-kernel: output must match
    attention computed over the host-dequantized slab (exactly the values
    the dense quantized path sees)."""
    import jax
    import jax.numpy as jnp

    from bloombee_tpu.kv.quant import dequantize, quantize
    from bloombee_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_int4,
    )

    rng = np.random.default_rng(0)
    B, H, HKV, hd = 2, 4, 2, 64
    page_size, n_pages, max_pages = 8, 16, 4
    q = jnp.asarray(rng.standard_normal((B, H, hd)), jnp.float32)
    k_dense = jnp.asarray(
        rng.standard_normal((n_pages * page_size, HKV, hd)), jnp.float32
    )
    v_dense = jnp.asarray(
        rng.standard_normal((n_pages * page_size, HKV, hd)), jnp.float32
    )
    kq, vq = quantize(k_dense), quantize(v_dense)
    pt = rng.integers(0, n_pages, (B, max_pages)).astype(np.int32)
    lens = np.asarray([25, 13], np.int32)

    got = np.asarray(
        paged_decode_attention_int4(
            q, kq, vq, jnp.asarray(pt), jnp.asarray(lens),
            page_size=page_size, scale=hd**-0.5, interpret=True,
            window=jnp.int32(0),
        )
    )

    kf = np.asarray(dequantize(kq, jnp.float32), np.float32)
    vf = np.asarray(dequantize(vq, jnp.float32), np.float32)
    qf = np.asarray(q)
    want = np.zeros_like(got)
    for b in range(B):
        toks = np.concatenate(
            [np.arange(p * page_size, (p + 1) * page_size) for p in pt[b]]
        )
        S = len(toks)
        for h in range(H):
            kvh = h // (H // HKV)
            lg = (qf[b, h] * hd**-0.5) @ kf[toks, kvh].T
            lg[np.arange(S) >= lens[b]] = -1e30
            w = np.exp(lg - lg.max())
            w /= w.sum()
            want[b, h] = w @ vf[toks, kvh]
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_int4_arena_uses_paged_kernel_and_matches_dense_path():
    """Executor end-to-end with an int4 KV arena: the paged kernel path
    (in-kernel dequant) matches the dense gather path (host-side dequant)
    on the same quantized values, and the kernel actually runs."""
    import asyncio
    import os

    import jax
    import jax.numpy as jnp

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.ops.pallas import paged_attention as pk
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=64,
        num_hidden_layers=2, vocab_size=64,
    )
    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.float32)
         for i in range(2)]
    )
    rng = np.random.default_rng(1)
    prefill = (rng.standard_normal((2, 21, 64)) * 0.1).astype(np.float32)
    steps = [(rng.standard_normal((2, 1, 64)) * 0.1).astype(np.float32)
             for _ in range(3)]

    calls = []
    orig = pk.paged_decode_attention_int4

    def spy(*a, **k):
        calls.append(True)
        return orig(*a, **k)

    async def run(paged):
        os.environ["BBTPU_PAGED_ATTENTION"] = "1" if paged else "0"
        os.environ["BBTPU_PAGED_INTERPRET"] = "1"
        os.environ["BBTPU_PAGED_MIN_CONTEXT"] = "0"
        try:
            manager = CacheManager(
                num_layers=2, num_pages=16, page_size=16,
                n_kv_heads=2, head_dim=64, dtype=jnp.float32, quant="int4",
            )
            ex = SpanExecutor(params, spec, manager,
                              compute_dtype=jnp.float32)
            async with manager.allocate(2, 64) as handle:
                outs = [ex.prefill(handle, prefill)]
                for s in steps:
                    outs.append(ex.decode(handle, s))
                return outs
        finally:
            for k in ("BBTPU_PAGED_ATTENTION", "BBTPU_PAGED_INTERPRET",
                      "BBTPU_PAGED_MIN_CONTEXT"):
                del os.environ[k]

    pk.paged_decode_attention_int4 = spy
    try:
        outs_paged = asyncio.run(run(True))
    finally:
        pk.paged_decode_attention_int4 = orig
    outs_dense = asyncio.run(run(False))
    assert calls, "int4 paged kernel never ran"
    for got, want in zip(outs_paged, outs_dense):
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_int4_paged_kernel_sliding_window():
    """int4 kernel honors the sliding window (shared softmax body): match
    the host-dequantized windowed reference."""
    import jax.numpy as jnp

    from bloombee_tpu.kv.quant import dequantize, quantize
    from bloombee_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_int4,
    )

    rng = np.random.default_rng(3)
    B, H, HKV, hd = 2, 4, 2, 64
    page_size, n_pages, max_pages = 8, 8, 4
    win = 11
    q = jnp.asarray(rng.standard_normal((B, H, hd)), jnp.float32)
    k_dense = jnp.asarray(
        rng.standard_normal((n_pages * page_size, HKV, hd)), jnp.float32
    )
    v_dense = jnp.asarray(
        rng.standard_normal((n_pages * page_size, HKV, hd)), jnp.float32
    )
    kq, vq = quantize(k_dense), quantize(v_dense)
    pt = rng.integers(0, n_pages, (B, max_pages)).astype(np.int32)
    lens = np.asarray([30, 17], np.int32)

    got = np.asarray(
        paged_decode_attention_int4(
            q, kq, vq, jnp.asarray(pt), jnp.asarray(lens),
            page_size=page_size, scale=hd**-0.5, interpret=True,
            window=jnp.int32(win),
        )
    )
    kf = np.asarray(dequantize(kq, jnp.float32), np.float32)
    vf = np.asarray(dequantize(vq, jnp.float32), np.float32)
    qf = np.asarray(q)
    want = np.zeros_like(got)
    for b in range(B):
        toks = np.concatenate(
            [np.arange(p * page_size, (p + 1) * page_size) for p in pt[b]]
        )
        S = len(toks)
        qpos = lens[b] - 1
        for h in range(H):
            kvh = h // (H // HKV)
            lg = (qf[b, h] * hd**-0.5) @ kf[toks, kvh].T
            pos = np.arange(S)
            lg[(pos >= lens[b]) | (pos <= qpos - win)] = -1e30
            w = np.exp(lg - lg.max())
            w /= w.sum()
            want[b, h] = w @ vf[toks, kvh]
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


from bloombee_tpu.ops.pallas.paged_attention import paged_chunk_attention


def dense_chunk_reference(
    q, k_slab, v_slab, page_table, lens, page_size, tree=None, window=0
):
    """[B, T, H, hd] reference with attend_paged's exact semantics: query
    token t sits at position lens-T+t; causal (or tree) masking over the
    paged context."""
    b, t_q, h, hd = q.shape
    hkv = k_slab.shape[1]
    g = h // hkv
    out = np.zeros((b, t_q, h, hd), np.float32)
    for i in range(b):
        slots = [
            p * page_size + o
            for p in page_table[i]
            for o in range(page_size)
        ]
        k = k_slab[np.asarray(slots)]
        v = v_slab[np.asarray(slots)]
        s = k.shape[0]
        pos = np.arange(s)
        start = lens[i] - t_q
        for t in range(t_q):
            q_pos = start + t
            if tree is None:
                mask = (pos < lens[i]) & (pos <= q_pos)
                if window > 0:
                    mask &= pos > q_pos - window
            else:
                in_step = (pos >= start) & (pos < lens[i])
                rel = np.clip(pos - start, 0, t_q - 1)
                mask = np.where(
                    in_step,
                    tree[i, t, rel] & (pos < lens[i]),
                    (pos < lens[i]) & (pos <= q_pos),
                )
            for head in range(h):
                kv = head // g
                logits = (
                    q[i, t, head].astype(np.float32)
                    @ k[:, kv].astype(np.float32).T
                ) * hd**-0.5
                logits = np.where(mask, logits, -1e30)
                p_att = np.exp(logits - logits.max())
                p_att /= p_att.sum()
                out[i, t, head] = p_att @ v[:, kv].astype(np.float32)
    return out


def _chunk_setup(rng, b, t_q, h, hkv, hd=64, page_size=16, n_phys=12):
    q = rng.standard_normal((b, t_q, h, hd)).astype(np.float32)
    k_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    v_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    page_table = np.array([[7, 2, 9, 0], [1, 4, 5, 8]], np.int32)[:b]
    lens = np.array([55, 38], np.int32)[:b]
    return q, k_slab, v_slab, page_table, lens


@pytest.mark.parametrize("hkv,h,t_q", [(2, 8, 4), (4, 4, 7), (1, 6, 3)])
def test_paged_chunk_causal_matches_dense(hkv, h, t_q):
    rng = np.random.default_rng(5)
    q, k_slab, v_slab, pt, lens = _chunk_setup(rng, 2, t_q, h, hkv)
    got = np.asarray(
        paged_chunk_attention(
            jnp.asarray(q), jnp.asarray(k_slab), jnp.asarray(v_slab),
            jnp.asarray(pt), jnp.asarray(lens), page_size=16,
            interpret=True,
        )
    )
    want = dense_chunk_reference(q, k_slab, v_slab, pt, lens, 16)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [5, 20])
def test_paged_chunk_sliding_window(window):
    rng = np.random.default_rng(6)
    q, k_slab, v_slab, pt, lens = _chunk_setup(rng, 2, 4, 8, 2)
    got = np.asarray(
        paged_chunk_attention(
            jnp.asarray(q), jnp.asarray(k_slab), jnp.asarray(v_slab),
            jnp.asarray(pt), jnp.asarray(lens), page_size=16,
            interpret=True, window=window,
        )
    )
    want = dense_chunk_reference(
        q, k_slab, v_slab, pt, lens, 16, window=window
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_paged_chunk_tree_matches_dense():
    """Tree-verify step: the [T, T] mask governs in-step visibility while
    the committed prefix stays fully visible (the speculative hot path the
    dense gather served before)."""
    rng = np.random.default_rng(7)
    t_q = 6
    q, k_slab, v_slab, pt, lens = _chunk_setup(rng, 2, t_q, 8, 2)
    # random lower-triangular-ish tree: node sees itself + its ancestors
    parents = np.array([-1, 0, 0, 1, 2, 3], np.int32)
    tm = np.zeros((t_q, t_q), bool)
    for n in range(t_q):
        node = n
        while node >= 0:
            tm[n, node] = True
            node = parents[node]
    tree = np.broadcast_to(tm, (2, t_q, t_q)).copy()
    got = np.asarray(
        paged_chunk_attention(
            jnp.asarray(q), jnp.asarray(k_slab), jnp.asarray(v_slab),
            jnp.asarray(pt), jnp.asarray(lens), page_size=16,
            tree_mask=jnp.asarray(tree), interpret=True, has_tree=True,
        )
    )
    want = dense_chunk_reference(
        q, k_slab, v_slab, pt, lens, 16, tree=tree
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_executor_tree_step_paged_matches_dense(monkeypatch):
    """Through the real executor: a tree decode step at paged-eligible
    context must produce the same output with the chunk kernel as with the
    dense gather path (lifts the old tb==1 gate)."""
    import asyncio

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params
    import jax.random as jr

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_hidden_layers=2, vocab_size=64,
    )
    params = stack_params(
        [init_block_params(jr.PRNGKey(i), spec) for i in range(2)]
    )

    def run(paged: bool):
        monkeypatch.setenv("BBTPU_PAGED_INTERPRET", "1" if paged else "")
        monkeypatch.setenv("BBTPU_PAGED_MIN_CONTEXT", "16")
        monkeypatch.setenv("BBTPU_PAGED_ATTENTION", "1" if paged else "")

        async def go():
            manager = CacheManager(
                num_layers=2, num_pages=32, page_size=4,
                n_kv_heads=2, head_dim=16, dtype=jnp.float32,
            )
            ex = SpanExecutor(
                params, spec, manager, compute_dtype=jnp.float32
            )
            rng = np.random.default_rng(1)
            async with manager.allocate(2, 64) as handle:
                pre = rng.standard_normal((2, 30, 64)).astype(np.float32)
                ex.prefill(handle, pre)
                t_q = 5
                step = rng.standard_normal((2, t_q, 64)).astype(np.float32)
                parents = np.array([-1, 0, 0, 1, 2], np.int32)
                tm = np.zeros((t_q, t_q), bool)
                for n in range(t_q):
                    node = n
                    while node >= 0:
                        tm[n, node] = True
                        node = parents[node]
                depths = np.array(
                    [[0, 1, 1, 2, 2]] * 2, np.int32
                )
                tree = np.broadcast_to(tm, (2, t_q, t_q)).copy()
                return ex.decode(
                    handle, step, commit=False, tree_mask=tree,
                    depths=depths,
                )

        return asyncio.run(go())

    dense = run(False)
    paged = run(True)
    np.testing.assert_allclose(
        np.asarray(paged, np.float32), np.asarray(dense, np.float32),
        rtol=2e-4, atol=2e-4,
    )


# ------------------------------------------------ ragged mixed-batch kernel
def dense_ragged_reference(
    q, k_slab, v_slab, page_table, lens, q_seq, q_pos, page_size, window=0
):
    """Row-by-row gather + masked softmax with the ragged kernel's exact
    semantics: row i belongs to sequence q_seq[i] (>= B = padding, emits
    zeros) and sees keys at positions <= q_pos[i] (within the window)."""
    r, h, hd = q.shape
    hkv = k_slab.shape[1]
    g = h // hkv
    b = page_table.shape[0]
    out = np.zeros((r, h, hd), np.float32)
    for i in range(r):
        sq = int(q_seq[i])
        if sq >= b:
            continue
        slots = [
            p * page_size + o
            for p in page_table[sq]
            for o in range(page_size)
        ]
        k = k_slab[np.asarray(slots)]
        v = v_slab[np.asarray(slots)]
        n = k.shape[0]
        pos = int(q_pos[i])
        mask = np.arange(n) <= pos
        if window > 0:
            mask &= np.arange(n) > pos - window
        for head in range(h):
            kv = head // g
            logits = (q[i, head].astype(np.float32) @
                      k[:, kv].astype(np.float32).T) * hd**-0.5
            logits = np.where(mask, logits, -1e30)
            p_att = np.exp(logits - logits.max())
            p_att = p_att / p_att.sum()
            out[i, head] = p_att @ v[:, kv].astype(np.float32)
    return out


@pytest.mark.parametrize("seed,window", [(0, 0), (1, 0), (2, 9), (3, 0)])
def test_paged_ragged_matches_dense_and_sibling_kernels(seed, window):
    """The parity gate for the mixed-batch kernel on RANDOMIZED ragged
    shapes (N decode rows + one multi-token chunk group + bucket-padding
    rows): paged_ragged_attention must match (a) the dense reference,
    (b) paged_decode_attention on the decode rows, and (c)
    paged_chunk_attention on the chunk member — the three paths a mixed
    group's members would otherwise take. Padding rows emit exact zeros."""
    from bloombee_tpu.ops.pallas.paged_attention import (
        paged_chunk_attention,
        paged_ragged_attention,
    )

    rng = np.random.default_rng(seed)
    page_size = int(rng.choice([8, 16]))
    hkv = int(rng.choice([1, 2]))
    h = hkv * int(rng.choice([2, 4]))
    hd = 64
    b = int(rng.integers(2, 5))
    max_pages = 4
    lens = rng.integers(
        6, page_size * max_pages + 1, size=b
    ).astype(np.int32)
    # disjoint shuffled physical pages per sequence; table padding = 0
    n_phys = b * max_pages + 2
    pool = rng.permutation(n_phys)
    page_table = np.zeros((b, max_pages), np.int32)
    off = 0
    for i in range(b):
        need = -(-int(lens[i]) // page_size)
        page_table[i, :need] = pool[off:off + need]
        off += need
    k_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    v_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)

    # ragged rows: every sequence but one contributes a single decode row
    # (pos = len-1); sequence `c` contributes a t-token chunk; then padding
    c = int(rng.integers(0, b))
    t = int(rng.integers(2, min(6, int(lens[c])) + 1))
    q_seq, q_pos = [], []
    for i in range(b):
        if i == c:
            q_seq.extend([c] * t)
            q_pos.extend(range(int(lens[c]) - t, int(lens[c])))
        else:
            q_seq.append(i)
            q_pos.append(int(lens[i]) - 1)
    n_pad = int(rng.integers(0, 3))
    q_seq.extend([b] * n_pad)
    q_pos.extend([0] * n_pad)
    q_seq = np.asarray(q_seq, np.int32)
    q_pos = np.asarray(q_pos, np.int32)
    r = len(q_seq)
    q = rng.standard_normal((r, h, hd)).astype(np.float32)

    got = np.asarray(
        paged_ragged_attention(
            jnp.asarray(q), jnp.asarray(k_slab), jnp.asarray(v_slab),
            jnp.asarray(page_table), jnp.asarray(lens),
            jnp.asarray(q_seq), jnp.asarray(q_pos),
            page_size=page_size, interpret=True, window=window,
        )
    )
    want = dense_ragged_reference(
        q, k_slab, v_slab, page_table, lens, q_seq, q_pos, page_size,
        window=window,
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    if n_pad:
        np.testing.assert_array_equal(got[r - n_pad:], 0.0)

    # (b) the decode rows match the single-token decode kernel
    dec_rows = [i for i in range(r - n_pad) if int(q_seq[i]) != c]
    dec_seqs = [int(q_seq[i]) for i in dec_rows]
    if dec_rows:
        dec_got = np.asarray(
            paged_decode_attention(
                jnp.asarray(q[dec_rows]), jnp.asarray(k_slab),
                jnp.asarray(v_slab), jnp.asarray(page_table[dec_seqs]),
                jnp.asarray(lens[dec_seqs]), page_size=page_size,
                interpret=True, window=window,
            )
        )
        np.testing.assert_allclose(
            got[dec_rows], dec_got, rtol=2e-5, atol=2e-5
        )

    # (c) the chunk member matches the multi-token chunk kernel
    chunk_rows = [i for i in range(r - n_pad) if int(q_seq[i]) == c]
    chunk_got = np.asarray(
        paged_chunk_attention(
            jnp.asarray(q[chunk_rows])[None], jnp.asarray(k_slab),
            jnp.asarray(v_slab), jnp.asarray(page_table[c:c + 1]),
            jnp.asarray(lens[c:c + 1]), page_size=page_size,
            interpret=True, window=window,
        )
    )
    np.testing.assert_allclose(
        got[chunk_rows], chunk_got[0], rtol=2e-5, atol=2e-5
    )


def dense_tree_ragged_reference(
    q, k_slab, v_slab, page_table, lens, nt, q_seq, q_pos, tree_rows,
    page_size,
):
    """Per-row numpy reference for the ragged TREE-verify mask: committed
    keys (pos < len - nt) are fully visible; in-step slot m of the row's
    own sequence is visible iff tree_rows[i, m]."""
    r, h, hd = q.shape
    hkv = k_slab.shape[1]
    g = h // hkv
    b = page_table.shape[0]
    out = np.zeros((r, h, hd), np.float32)
    for i in range(r):
        sq = int(q_seq[i])
        if sq >= b:
            continue
        slots = [
            p * page_size + o
            for p in page_table[sq]
            for o in range(page_size)
        ]
        k = k_slab[np.asarray(slots)]
        v = v_slab[np.asarray(slots)]
        n = k.shape[0]
        ss = int(lens[sq]) - int(nt[sq])
        pos = np.arange(n)
        mask = pos < ss
        for m in range(int(nt[sq])):
            if tree_rows[i, m]:
                mask |= pos == ss + m
        mask &= pos < int(lens[sq])
        for head in range(h):
            kv = head // g
            logits = (q[i, head].astype(np.float32) @
                      k[:, kv].astype(np.float32).T) * hd**-0.5
            logits = np.where(mask, logits, -1e30)
            p_att = np.exp(logits - logits.max())
            p_att = p_att / p_att.sum()
            out[i, head] = p_att @ v[:, kv].astype(np.float32)
    return out


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_paged_ragged_tree_matches_dense_reference(seed):
    """Parity gate for the ragged TREE-verify kernel variant: N sessions'
    linearized trees (random ancestor-or-self structures, differing sizes,
    zero-padded tree rows) over shuffled disjoint pages must match the
    per-row dense reference, and padding rows emit exact zeros."""
    from bloombee_tpu.ops.pallas.paged_attention import (
        paged_ragged_attention,
    )
    from bloombee_tpu.spec.tree import DraftTree, tree_attention_mask

    rng = np.random.default_rng(seed)
    page_size = int(rng.choice([8, 16]))
    hkv = int(rng.choice([1, 2]))
    h = hkv * int(rng.choice([2, 4]))
    hd = 64
    b = int(rng.integers(2, 5))
    max_pages = 4
    # committed context per sequence, then a tree of t_b in-step tokens
    committed = rng.integers(5, 20, size=b).astype(np.int32)
    t_max = 8
    nts = rng.integers(2, t_max + 1, size=b).astype(np.int32)
    lens = (committed + nts).astype(np.int32)
    assert int(lens.max()) <= page_size * max_pages

    n_phys = b * max_pages + 2
    pool = rng.permutation(n_phys)
    page_table = np.zeros((b, max_pages), np.int32)
    off = 0
    for i in range(b):
        need = -(-int(lens[i]) // page_size)
        page_table[i, :need] = pool[off:off + need]
        off += need
    k_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)
    v_slab = rng.standard_normal(
        (n_phys * page_size, hkv, hd)
    ).astype(np.float32)

    q_seq, q_pos = [], []
    tree_rows = []
    for i in range(b):
        t = int(nts[i])
        # random ancestor-or-self tree: node j's parent uniform in [-1, j)
        parents = np.asarray(
            [-1] + [int(rng.integers(-1, j)) for j in range(1, t)],
            np.int64,
        )
        tree = DraftTree(
            tokens=np.zeros(t, np.int64), parents=parents
        )
        tm = tree_attention_mask(tree)
        depths = tree.depths()
        q_seq.extend([i] * t)
        q_pos.extend((int(committed[i]) + depths).tolist())
        for row in range(t):
            tr = np.zeros(t_max, np.int32)
            tr[:t] = tm[row]
            tree_rows.append(tr)
    n_pad = int(rng.integers(0, 3))
    for _ in range(n_pad):
        q_seq.append(b)
        q_pos.append(0)
        tree_rows.append(np.zeros(t_max, np.int32))
    q_seq = np.asarray(q_seq, np.int32)
    q_pos = np.asarray(q_pos, np.int32)
    tree_rows = np.stack(tree_rows)
    r = len(q_seq)
    q = rng.standard_normal((r, h, hd)).astype(np.float32)

    got = np.asarray(
        paged_ragged_attention(
            jnp.asarray(q), jnp.asarray(k_slab), jnp.asarray(v_slab),
            jnp.asarray(page_table), jnp.asarray(lens),
            jnp.asarray(q_seq), jnp.asarray(q_pos),
            page_size=page_size, interpret=True, window=0,
            nt=jnp.asarray(nts), tree_rows=jnp.asarray(tree_rows),
            has_tree=True,
        )
    )
    want = dense_tree_ragged_reference(
        q, k_slab, v_slab, page_table, lens, nts, q_seq, q_pos, tree_rows,
        page_size,
    )
    np.testing.assert_allclose(
        got[: r - n_pad], want[: r - n_pad], rtol=2e-5, atol=2e-5
    )
    if n_pad:
        np.testing.assert_array_equal(got[r - n_pad:], 0.0)
