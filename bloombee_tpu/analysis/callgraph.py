"""Module-level call graph + reachability for bbtpu-lint v2.

BB002/BB003 (PR 9) are intraprocedural: they see `with lock: time.sleep()`
but not `with lock: flush()` where flush() sleeps three helpers down —
and the bugs that actually ship are the second kind. This module builds
a call graph over the analyzed files ONCE per run and gives the
concurrency rules two primitives:

- :meth:`CallGraph.resolve` — best-effort resolution of a call site to a
  known function, using heuristics tuned for this codebase:
  self-methods, same-file functions, from-imports/module aliases mapped
  onto analyzed paths, a small known-singleton receiver map
  (``manager``/``self.manager`` is always the CacheManager, ``conn`` a
  wire Connection, ...), and a unique-global-name fallback.
- :meth:`CallGraph.reach` — reverse-BFS shortest call chains from every
  function to a target set, so a finding can print the full
  ``caller -> helper -> blocking site`` trace.

Deliberate under-approximations (missed edges beat false chains):

- callables passed as ARGUMENTS (``compute.submit(fn)``,
  ``asyncio.to_thread(fn)``) create no edge — which is exactly right for
  the lock rules, since those run on another thread/later tick, outside
  the caller's critical section;
- nested ``def``/``lambda`` bodies are skipped (they run when called,
  not where defined) and are not indexed;
- unresolvable receivers resolve to nothing rather than to everything.

Pure stdlib, like the rest of the lint.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque

from bloombee_tpu.analysis.core import SourceFile

# Known-singleton receivers: attribute/variable names that, by package
# convention, always hold an instance of one specific class. Lets
# `self.manager.reserve(...)` resolve without type inference. Keep this
# list short and certain — a wrong entry fabricates call chains.
RECEIVER_CLASSES: dict[str, str] = {
    "manager": "CacheManager",
    "cache_manager": "CacheManager",
    "compute": "ComputeQueue",
    "executor": "SpanExecutor",
    "conn": "Connection",
    "peers": "_PeerPool",
    "registry": "RegistryClient",
    "reg": "RegistryClient",
    "table": "PagedKVTable",
}


@dataclasses.dataclass
class FuncInfo:
    qname: str  # "path::Class.method" or "path::func"
    path: str
    name: str
    cls: str | None
    node: ast.FunctionDef | ast.AsyncFunctionDef
    sf: SourceFile

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)

    @property
    def display(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


def body_walk(node: ast.AST):
    """Walk a function body WITHOUT descending into nested defs/lambdas
    (their bodies run when called, not under the enclosing context).
    Breadth-first in source order, so simple `alias = lock` assignments
    are seen before the `with alias:` statements that use them."""
    queue = deque(ast.iter_child_nodes(node))
    while queue:
        n = queue.popleft()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        queue.extend(ast.iter_child_nodes(n))


class CallGraph:
    def __init__(self, files: list[SourceFile]):
        self._paths = {sf.path for sf in files}
        self.functions: dict[str, FuncInfo] = {}
        # (path, cls-or-None, name) -> qname
        self._index: dict[tuple[str, str | None, str], str] = {}
        # class name -> {method name -> qname}; first definition wins
        self._class_methods: dict[str, dict[str, str]] = {}
        # bare top-level function name -> [qname, ...] across all files
        self._global_funcs: dict[str, list[str]] = {}
        # per path: alias -> module path / name -> (module path, orig name)
        self._module_alias: dict[str, dict[str, str]] = {}
        self._symbol_import: dict[str, dict[str, tuple[str, str]]] = {}

        for sf in files:
            self._index_file(sf)
        # edges resolved after the full index exists
        self.edges: dict[str, list[tuple[str, ast.Call]]] = {}
        self._reverse: dict[str, set[str]] = {}
        for fi in self.functions.values():
            self._collect_edges(fi)

    # ------------------------------------------------------------ indexing
    def _index_file(self, sf: SourceFile) -> None:
        self._module_alias[sf.path] = {}
        self._symbol_import[sf.path] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mp = self._module_to_path(a.name)
                    if mp:
                        alias = a.asname or a.name.split(".")[-1]
                        self._module_alias[sf.path][alias] = mp
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(sf.path, node)
                if base is None:
                    continue
                for a in node.names:
                    alias = a.asname or a.name
                    sub = self._module_to_path(
                        f"{base}.{a.name}" if base else a.name
                    )
                    if sub:
                        # `from pkg import module` — alias is a module
                        self._module_alias[sf.path][alias] = sub
                        continue
                    mp = self._module_to_path(base)
                    if mp:
                        self._symbol_import[sf.path][alias] = (mp, a.name)
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(sf, node, None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._add_func(sf, item, node.name)

    def _add_func(self, sf: SourceFile, node, cls: str | None) -> None:
        disp = f"{cls}.{node.name}" if cls else node.name
        qname = f"{sf.path}::{disp}"
        if qname in self.functions:  # redefinition: last wins, like Python
            pass
        self.functions[qname] = FuncInfo(
            qname=qname, path=sf.path, name=node.name, cls=cls,
            node=node, sf=sf,
        )
        self._index[(sf.path, cls, node.name)] = qname
        if cls is None:
            self._global_funcs.setdefault(node.name, []).append(qname)
        else:
            self._class_methods.setdefault(cls, {}).setdefault(
                node.name, qname
            )

    def _module_to_path(self, dotted: str) -> str | None:
        if not dotted:
            return None
        base = dotted.replace(".", "/")
        for cand in (f"{base}.py", f"{base}/__init__.py"):
            if cand in self._paths:
                return cand
        return None

    def _import_base(self, path: str, node: ast.ImportFrom) -> str | None:
        """Dotted base module of an ImportFrom, resolving relative
        imports against the importing file's package."""
        if node.level == 0:
            return node.module or None
        parts = path.rsplit("/", 1)[0].split("/")
        if node.level - 1 > len(parts):
            return None
        if node.level > 1:
            parts = parts[: len(parts) - (node.level - 1)]
        base = ".".join(parts)
        if node.module:
            base = f"{base}.{node.module}" if base else node.module
        return base or None

    # ----------------------------------------------------------- resolution
    def resolve(
        self, path: str, cls: str | None, call: ast.Call
    ) -> str | None:
        """Best-effort: qname of the called function, or None."""
        f = call.func
        if isinstance(f, ast.Name):
            q = self._index.get((path, None, f.id))
            if q:
                return q
            sym = self._symbol_import.get(path, {}).get(f.id)
            if sym:
                return self._index.get((sym[0], None, sym[1]))
            cands = self._global_funcs.get(f.id, ())
            return cands[0] if len(cands) == 1 else None
        if not isinstance(f, ast.Attribute):
            return None
        m, v = f.attr, f.value
        if isinstance(v, ast.Name):
            if v.id == "self" and cls is not None:
                return self._index.get((path, cls, m))
            mp = self._module_alias.get(path, {}).get(v.id)
            if mp:
                return self._index.get((mp, None, m))
            cname = RECEIVER_CLASSES.get(v.id)
            if cname:
                return self._class_methods.get(cname, {}).get(m)
            sym = self._symbol_import.get(path, {}).get(v.id)
            if sym:  # `from pkg import Class` then Class.staticmethod()
                return self._class_methods.get(sym[1], {}).get(m)
            return None
        if (
            isinstance(v, ast.Attribute)
            and isinstance(v.value, ast.Name)
            and v.value.id == "self"
        ):
            cname = RECEIVER_CLASSES.get(v.attr)
            if cname:
                return self._class_methods.get(cname, {}).get(m)
        return None

    def _collect_edges(self, fi: FuncInfo) -> None:
        out: list[tuple[str, ast.Call]] = []
        nodes = list(body_walk(fi.node))
        awaited = {
            id(n.value)
            for n in nodes
            if isinstance(n, ast.Await) and isinstance(n.value, ast.Call)
        }
        for n in nodes:
            if isinstance(n, ast.Call):
                q = self.resolve(fi.path, fi.cls, n)
                if q is None:
                    continue
                # calling an async function without awaiting only CREATES
                # the coroutine — `self._spawn(self._read_loop())` runs
                # the body on a later tick, not here, so no edge
                if self.functions[q].is_async and id(n) not in awaited:
                    continue
                out.append((q, n))
                self._reverse.setdefault(q, set()).add(fi.qname)
        self.edges[fi.qname] = out

    # --------------------------------------------------------- reachability
    def reach(self, targets: set[str]) -> dict[str, tuple[str, ...]]:
        """For every function that can reach a target through call edges,
        the SHORTEST chain of qnames from it to that target (a target's
        own chain is just ``(target,)``). Reverse BFS, so recursion and
        call-graph cycles terminate."""
        nxt: dict[str, str] = {}
        dist: dict[str, int] = {}
        dq: deque[str] = deque()
        for t in targets:
            if t in self.functions:
                dist[t] = 0
                dq.append(t)
        while dq:
            q = dq.popleft()
            for caller in self._reverse.get(q, ()):
                if caller not in dist:
                    dist[caller] = dist[q] + 1
                    nxt[caller] = q
                    dq.append(caller)
        chains: dict[str, tuple[str, ...]] = {}
        for q in dist:
            chain = [q]
            while chain[-1] in nxt:
                chain.append(nxt[chain[-1]])
            chains[q] = tuple(chain)
        return chains

    def display(self, qname: str) -> str:
        fi = self.functions.get(qname)
        return fi.display if fi else qname.rsplit("::", 1)[-1]

    def format_chain(self, chain: tuple[str, ...]) -> str:
        return " -> ".join(self.display(q) for q in chain)
