"""Sequence-parallel SERVING prefill: long-context prefill over an sp mesh.

The long-context serving story (SURVEY §5): a single chip's prefill
latency grows linearly with prompt length, so a server with idle local
chips can spread ONE session's prefill over them — each chip computes a
contiguous sequence chunk with ring attention streaming K/V blocks around
the `sp` axis (parallel/ring_attention.py), and every layer's K/V chunks
are collected into the ordinary paged arena afterwards. DECODE then
continues on the unmodified single-chip paged path: sequence parallelism
is a PREFILL accelerator here, not a resident sharding, which is exactly
the shape of the problem (prefill is compute-bound and parallel over
tokens; decode is latency-bound and serial).

The reference has no sequence/context parallelism at all (SURVEY §2.8);
this composes the training-side ring attention with the serving arena.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.parallel.spmd import (
    PARAM_SPECS,
    _check_known_keys,
    _spmd_unsupported,
    spmd_span_forward_kv,
)


def make_sp_mesh(sp: int, devices=None) -> Mesh:
    """(tp=1, sp) mesh over the local chips: the SPMD body wants both
    axes; serving sp keeps tp degenerate (compose later if needed)."""
    devices = devices if devices is not None else jax.devices()
    if sp > len(devices):
        raise ValueError(f"sp={sp} needs {sp} devices, have {len(devices)}")
    return Mesh(
        np.asarray(devices[:sp]).reshape(1, sp), ("tp", "sp")
    )


def sp_unsupported(spec: ModelSpec, params: dict) -> str | None:
    """Why this span cannot run sp prefill; None when it can. Inherits the
    SPMD body's family limits (ring attention: no windows/ALiBi/soft-cap)
    plus serving-side ones (fresh full-context prefill only)."""
    reason = _spmd_unsupported(spec, params)
    if reason is not None:
        return reason
    unknown = set(params) - set(PARAM_SPECS)
    if unknown:
        return f"no sharding specs for params {sorted(unknown)}"
    return None


def _sp_spec(key: str) -> P:
    """PARAM_SPECS with the training mesh's 'pp' layer axis dropped (the
    sp serving mesh has no pipeline axis; whole span on every chip)."""
    return P(*(None if a == "pp" else a for a in PARAM_SPECS[key]))


def place_sp_params(params: dict, mesh: Mesh) -> dict:
    """Replicate span params over the sp mesh (tp is degenerate, and the
    sequence axis never shards weights)."""
    _check_known_keys(params)
    return {
        k: jax.device_put(v, NamedSharding(mesh, _sp_spec(k)))
        for k, v in params.items()
    }


@functools.lru_cache(maxsize=None)
def _sp_prefill_fn(mesh: Mesh, spec: ModelSpec, param_keys: tuple):
    fwd = jax.shard_map(
        functools.partial(
            spmd_span_forward_kv, spec=spec, sp_axis="sp", tp_axis="tp"
        ),
        mesh=mesh,
        in_specs=(
            {k: _sp_spec(k) for k in param_keys},
            P(None, "sp", None),
        ),
        out_specs=(
            P(None, "sp", None),
            P(None, None, "sp", None, None),
            P(None, None, "sp", None, None),
        ),
        check_vma=False,
    )
    return jax.jit(fwd)


def sp_prefill(
    params: dict,  # stacked span params, already placed via place_sp_params
    hidden,  # [B, T, D] (np or jax), T % sp == 0 (caller pads)
    mesh: Mesh,
    *,
    spec: ModelSpec,
):
    """Run the whole span's prefill over the sp mesh from position 0.

    Returns (hidden_out [B, T, D], k [L, B, T, Hkv, hd], v [...]): k is
    post-rotary exactly like the serving layer body writes it, so the
    caller scatters k/v straight into the paged arena and decode picks up
    where prefill left off."""
    reason = sp_unsupported(spec, params)
    if reason is not None:
        raise NotImplementedError(f"sp prefill unavailable: {reason}")
    t = np.shape(hidden)[1]
    sp = mesh.devices.shape[1]
    if t % sp:
        raise ValueError(f"sp prefill needs T % sp == 0 (T={t}, sp={sp})")
    hidden = jax.device_put(
        jnp.asarray(hidden), NamedSharding(mesh, P(None, "sp", None))
    )
    fn = _sp_prefill_fn(mesh, spec, tuple(sorted(params)))
    return fn(params, hidden)
