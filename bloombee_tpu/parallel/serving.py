"""Tensor-parallel SERVING: the paged span step partitioned over a tp mesh.

The reference serves real decode under tensor parallelism with hand-rolled
per-device CUDA streams and stream all-reduces
(/root/reference/src/bloombee/server/flexgen_tensor_parallel.py:540-828:
row/col weight slices, `_reduce_partials`, per-shard KV merge). The TPU
idiom is the opposite of hand-scheduling: annotate the *placement* of the
weights and the KV arena over the mesh and let GSPMD partition the very same
`span_step_packed` computation, inserting the Megatron collectives (psum
after o_proj and down_proj) over ICI automatically.

Sharding layout (serving mesh has one axis, "tp"):
- q/k/v projections: output dim sharded -> each device computes its local
  heads. Attention is embarrassingly parallel over heads, so the paged
  gather/scatter and masks replicate per shard.
- o_proj / down_proj: input dim sharded -> local partial matmul, XLA psums.
- KV arena: the kv-head dim sharded -> each device holds its heads' pages
  (the per-shard KV merge of the reference's `_merge_cache_parts` never
  needs to happen).
- Mixtral experts: the expert dim shards over tp = true expert parallelism
  (the reference runs all experts densely on every device).

Requires num_attention_heads % tp == 0; homogeneous spans also require
num_key_value_heads % tp == 0, while HETEROGENEOUS spans replicate the K/V
of layers whose own KV-head count does not divide tp (gemma-4 full layers
with a single KV head) and shard everything else — see
place_hetero_span_params / place_hetero_arena.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bloombee_tpu.models.spec import ModelSpec

# specs for stacked span params [L, ...] (L unsharded: one server owns the
# whole span; cf. parallel/spmd.py PARAM_SPECS which also shards pp)
SERVING_PARAM_SPECS = {
    "input_layernorm": P(None, None),
    "input_layernorm_bias": P(None, None),
    "post_attention_layernorm": P(None, None),
    "post_attention_layernorm_bias": P(None, None),
    "mlp_layernorm": P(None, None),
    "mlp_layernorm_bias": P(None, None),
    "pre_feedforward_layernorm": P(None, None),
    "post_feedforward_layernorm": P(None, None),
    "q_proj": P(None, None, "tp"),
    "k_proj": P(None, None, "tp"),
    "v_proj": P(None, None, "tp"),
    "o_proj": P(None, "tp", None),
    "q_bias": P(None, "tp"),
    "k_bias": P(None, "tp"),
    "v_bias": P(None, "tp"),
    "o_bias": P(None, None),
    "gate_proj": P(None, None, "tp"),
    "up_proj": P(None, None, "tp"),
    "down_proj": P(None, "tp", None),
    "gate_bias": P(None, "tp"),
    "up_bias": P(None, "tp"),
    "down_bias": P(None, None),
    "q_norm": P(None, None),
    "k_norm": P(None, None),
    "router": P(None, None, None),
    "experts_gate": P(None, "tp", None, None),
    "experts_up": P(None, "tp", None, None),
    "experts_down": P(None, "tp", None, None),
}

# KV arena [L, S_tot, Hkv, hd]: heads shard over tp
ARENA_SPEC = P(None, None, "tp", None)


def make_serving_mesh(tp: int, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if tp > len(devices):
        raise ValueError(f"tp={tp} needs {tp} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:tp]), ("tp",))


def check_tp_divides(spec: ModelSpec, tp: int, hetero: bool = False) -> None:
    """hetero=True skips the kv-head check only: per-layer KV geometry is
    handled by the per-layer placement (layers whose kv heads don't divide
    replicate their K/V); q heads and experts are uniform either way."""
    if spec.num_attention_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_attention_heads="
            f"{spec.num_attention_heads}"
        )
    if not hetero and spec.num_key_value_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_key_value_heads="
            f"{spec.num_key_value_heads} (KV-head replication only exists "
            "on the heterogeneous path)"
        )
    if spec.num_experts and spec.num_experts % tp:
        raise ValueError(
            f"tp={tp} must divide num_experts={spec.num_experts}"
        )


def _quant_leaf_spec(base, shape, tp):
    """Sharding spec for one leaf of a quantized weight: keep the base
    placement wherever the leaf's dim divides tp, replicate the rest.
    Handles every layout by shape alone: int8 scales [L, 1, out] drop an
    input-dim "tp" (size 1), int4 group scales [L, in/GROUP, out] keep it,
    packed int4 codes [L, in/2, out] keep it, expert leaves [L, E, ...]
    keep the expert-dim shard."""
    spec = tuple(
        None if (s == "tp" and shape[i] % tp != 0) else s
        for i, s in enumerate(base)
    )
    return P(*spec)


def place_span_params(params: dict, mesh: Mesh) -> dict:
    """Commit stacked span params to the serving mesh (tp-sharded).

    Quantized projections (models/wquant.py QuantWeight) shard like their
    dense counterparts: codes follow the weight's row/col placement, and
    each scale/zero leaf keeps the shards' scales local (the dequantize is
    an elementwise producer, so GSPMD keeps it fused shard-local and the
    Megatron psums are unchanged — the composition the reference builds by
    hand from compression.py + flexgen_tensor_parallel.py)."""
    from bloombee_tpu.models.wquant import QuantWeight

    tp = mesh.devices.size
    out = {}
    for k, v in params.items():
        base = SERVING_PARAM_SPECS[k]
        if isinstance(v, QuantWeight):
            def put(leaf):
                if leaf is None:
                    return None
                return jax.device_put(
                    leaf,
                    NamedSharding(
                        mesh, _quant_leaf_spec(base, leaf.shape, tp)
                    ),
                )

            out[k] = QuantWeight(
                codes=put(v.codes), scale=put(v.scale), zero=put(v.zero)
            )
        else:
            out[k] = jax.device_put(v, NamedSharding(mesh, base))
    return out


def place_arena(arena: dict, mesh: Mesh) -> dict:
    """Commit the KV arena to the serving mesh (kv heads sharded)."""
    return {
        k: jax.device_put(v, NamedSharding(mesh, ARENA_SPEC))
        for k, v in arena.items()
    }


def replicated(x, mesh: Mesh):
    """Commit a host array replicated over the mesh (step payloads/masks)."""
    return jax.device_put(x, NamedSharding(mesh, P()))


def _layer_spec(base, shape, tp, kv_replicate: bool):
    """Per-layer (no leading L dim) spec from the stacked base: delegate
    to the shared drop-tp-where-indivisible rule; `kv_replicate` forces
    replication regardless of the flattened dim (a single KV head whose
    head_dim happens to divide tp must NOT be split WITHIN the head — the
    arena keys the same decision on the layer's KV-head count)."""
    if kv_replicate:
        return P(*(None for _ in base[1:]))
    return _quant_leaf_spec(base[1:], shape, tp)


def _place_one_layer(params: dict, mesh: Mesh, kv_replicate: bool) -> dict:
    """Commit ONE layer's (unstacked) param dict to the tp mesh — the
    shared leaf-placement body of the hetero and weight-offload paths.
    `kv_replicate` forces the k/v leaves replicated (a layer whose KV-head
    count doesn't divide tp)."""
    from bloombee_tpu.models.wquant import QuantWeight

    tp = mesh.devices.size
    out = {}
    for key, leaf in params.items():
        base = SERVING_PARAM_SPECS[key]
        kv_rep = kv_replicate and key.startswith(("k_", "v_"))

        def put(x, base=base, kv_rep=kv_rep):
            if x is None:
                return None
            return jax.device_put(
                x,
                NamedSharding(mesh, _layer_spec(base, x.shape, tp, kv_rep)),
            )

        if isinstance(leaf, QuantWeight):
            out[key] = QuantWeight(
                codes=put(leaf.codes), scale=put(leaf.scale),
                zero=put(leaf.zero),
            )
        else:
            out[key] = put(leaf)
    return out


def place_hetero_span_params(
    layer_params: tuple, mesh: Mesh, spec: ModelSpec, start_block: int = 0
) -> tuple:
    """Commit per-layer param dicts (heterogeneous spans) to the tp mesh:
    each layer shards like its stacked counterpart where its dims divide.
    K/V projections follow the LAYER'S KV-HEAD count (the same rule the
    arena placement uses): layers whose kv heads don't divide tp
    replicate their k/v leaves, so K/V writes stay collective-free."""
    tp = mesh.devices.size
    return tuple(
        _place_one_layer(
            params, mesh,
            kv_replicate=spec.kv_heads_for_layer(start_block + i) % tp != 0,
        )
        for i, params in enumerate(layer_params)
    )


def place_layer_params(params: dict, mesh: Mesh) -> dict:
    """Per-step placement of a weight-offloaded host layer: the same
    row/col sharding as its stacked counterpart, so the streamed H2D
    bytes split across the tp chips instead of replicating."""
    return _place_one_layer(params, mesh, kv_replicate=False)


def place_arena_for(spec: ModelSpec, arena: dict, mesh: Mesh) -> dict:
    """Arena placement dispatch shared by executor init and the
    post-failure rebuild (one site decides hetero vs dense, so a rebuilt
    arena can never be placed with the wrong helper)."""
    if spec.heterogeneous:
        return place_hetero_arena(arena, mesh)
    return place_arena(arena, mesh)


def place_hetero_arena(arena: dict, mesh: Mesh) -> dict:
    """Commit per-layer KV slabs to the tp mesh: a layer's KV heads shard
    when they divide tp, else that layer's slab replicates (the scatter of
    sharded K/V into a replicated slab is an all-gather GSPMD inserts)."""
    tp = mesh.devices.size

    def put(slab):
        def leaf_put(x):
            # slab leaves are [1, S_tot, Hkv_l, ...]; shard the head dim
            spec = (
                P(None, None, "tp", None)
                if x.shape[2] % tp == 0 else P()
            )
            return jax.device_put(x, NamedSharding(mesh, spec))

        return jax.tree.map(leaf_put, slab)

    return {
        "k": tuple(put(s) for s in arena["k"]),
        "v": tuple(put(s) for s in arena["v"]),
    }
