"""int4 KV quantization (reference flexgen_utils/compression.py:22-210).

- round-trip error bound for the group-wise quantizer
- capacity: the int4 arena stores >= 3x more tokens per byte than bf16
- serving parity: an int4-arena server's logits stay close to the dense
  server's (KV quantization tolerance, not exactness)
- parked-host quantization round trip through park/unpark
"""

import asyncio

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.kv.arena import make_arena
from bloombee_tpu.kv.cache_manager import CacheManager
from bloombee_tpu.kv.quant import QuantSlab, dequantize, quantize, slab_nbytes


def test_quant_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 4, 64)).astype(np.float32))
    back = dequantize(quantize(x), jnp.float32)
    # 15 levels over each group's range: error <= range / 30 (+ f16 scale
    # rounding slack); normal data range within a 32-group is ~4-5 sigma
    err = np.abs(np.asarray(back) - np.asarray(x))
    group_range = np.asarray(
        x.reshape(64, 4, 2, 32).max(-1) - x.reshape(64, 4, 2, 32).min(-1)
    )
    bound = np.repeat(group_range / 30.0, 32, axis=-1).reshape(x.shape) + 2e-2
    assert (err <= bound).all(), err.max()


def test_quant_capacity_3x():
    dense = make_arena(2, 16, 16, 8, 128, jnp.bfloat16)
    q4 = make_arena(2, 16, 16, 8, 128, jnp.bfloat16, quant="int4")
    ratio = slab_nbytes(dense["k"]) / slab_nbytes(q4["k"])
    assert ratio >= 3.0, ratio
    # same byte budget -> >= 3x the pages -> >= 3x tokens admitted
    assert int(16 * ratio) >= 48


def test_int4_server_logits_close(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)

    async def logits_with(kv_quant):
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(
            model_uid="t", start=0, end=3, model_dir=str(tmp_path),
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, kv_quant=kv_quant,
        )
        await s.start()
        dm = DistributedModelForCausalLM.from_pretrained(
            str(tmp_path), rc(), model_uid="t"
        )
        input_ids = np.arange(10)[None, :] % config.vocab_size
        async with dm.inference_session(16, 1) as sess:
            hidden = dm.embed(input_ids)
            out = await sess.step(hidden)
        res = dm.logits(out)
        await s.stop()
        await reg.stop()
        return res

    dense = asyncio.run(logits_with(None))
    q4 = asyncio.run(logits_with("int4"))
    # int4 KV error is bounded per-group; logits drift but ranks hold for a
    # prefill this short
    np.testing.assert_allclose(q4, dense, atol=0.15, rtol=0.1)
    assert (np.argmax(q4, -1) == np.argmax(dense, -1)).mean() >= 0.8


def test_int4_decode_steps_consistent():
    """Stepwise decode through the paged executor with an int4 arena: the
    step outputs must track the dense-arena outputs."""
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_hidden_layers=2, vocab_size=64,
    )
    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec) for i in range(2)]
    )

    async def run(quant):
        manager = CacheManager(
            num_layers=2, num_pages=32, page_size=4, n_kv_heads=2,
            head_dim=16, dtype=jnp.float32, quant=quant,
        )
        ex = SpanExecutor(params, spec, manager, compute_dtype=jnp.float32)
        rng = np.random.default_rng(0)
        outs = []
        async with manager.allocate(2, 16) as handle:
            outs.append(ex.prefill(
                handle, rng.standard_normal((2, 6, 64)).astype(np.float32)
            ))
            for _ in range(3):
                outs.append(ex.decode(
                    handle,
                    rng.standard_normal((2, 1, 64)).astype(np.float32),
                ))
        return outs

    dense = asyncio.run(run(None))
    q4 = asyncio.run(run("int4"))
    for a, b in zip(dense, q4):
        # int4 KV drift through random-init blocks: direction preserved and
        # bounded relative to the activation scale (measured ~0.997 / ~8%)
        cos = float(
            (a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-9)
        )
        assert cos > 0.99, cos
        assert np.abs(a - b).max() < 0.12 * np.abs(a).max()


def test_park_unpark_quantized_host(monkeypatch):
    """Dense arena + BBTPU_PARK_QUANT: parked KV is int4 on host; unpark
    restores within quantization tolerance and the page table survives."""
    monkeypatch.setenv("BBTPU_PARK_QUANT", "1")

    async def run():
        manager = CacheManager(
            num_layers=2, num_pages=16, page_size=4, n_kv_heads=2,
            head_dim=32, dtype=jnp.float32,
        )
        rng = np.random.default_rng(1)
        async with manager.allocate(1, 12) as handle:
            slots = manager.write_slots(handle, 6)
            k_new = rng.standard_normal((6, 2, 32)).astype(np.float32)
            v_new = rng.standard_normal((6, 2, 32)).astype(np.float32)
            from bloombee_tpu.kv import arena as arena_ops

            ak, av = arena_ops.arena_write(
                manager.arena["k"][0], manager.arena["v"][0],
                jnp.asarray(slots), jnp.asarray(k_new), jnp.asarray(v_new),
            )
            manager.arena["k"] = manager.arena["k"].at[0].set(ak)
            manager.arena["v"] = manager.arena["v"].at[0].set(av)
            sid = handle.seq_ids[0]
            before = np.asarray(manager.arena["k"][0, slots])
            manager.park_sequence(sid)
            parked_k = manager._parked[sid].resolve()[0]
            assert isinstance(parked_k, QuantSlab)  # int4 on host
            manager.unpark_sequence(sid)
            after_slots = manager.table.prefix_slots(sid)
            after = np.asarray(manager.arena["k"][0, after_slots])
            # int4 over one 32-wide group of ~N(0,1): range ~4-5 sigma,
            # quantization step = range/15 -> error bound ~range/30 ~ 0.17
            np.testing.assert_allclose(after, before, atol=0.2)

    asyncio.run(run())
