"""Speculative decoding: client drafter + tree verify over the swarm.

Mirrors the reference's Llama speculative stack
(/root/reference/src/bloombee/models/llama/speculative_model.py,
spe_dec_tree.py, spec_decoding_verify.py, spec_decoding_drafter.py): a
client-side drafter builds token trees, one distributed forward verifies the
whole linearized tree against the target model (tree attention mask +
per-node depth positions), SpecInfer-style accept picks the surviving path,
and servers compact the surviving KV slots onto the committed prefix
(on-device gather instead of the reference's async reorder thread).
"""

from bloombee_tpu.spec.tree import DraftTree, tree_attention_mask
from bloombee_tpu.spec.verify import accept_greedy, accept_sampling
from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel

__all__ = [
    "DraftTree",
    "tree_attention_mask",
    "accept_greedy",
    "accept_sampling",
    "GreedyTreeDrafter",
    "LocalJaxDraftModel",
]
