"""Client: embeddings + LM head locally, blocks via the swarm.

Mirrors /root/reference/src/bloombee/client/ — RemoteSequenceManager
(routing), InferenceSession (stateful decode with retry/re-route/replay), and
the distributed model facade with generate(). All client math is jax (runs on
CPU or any accelerator — the reference's `device='xla'` goal).
"""

from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
from bloombee_tpu.client.session import InferenceSession
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.client.classification import (
    DistributedModelForSequenceClassification,
)

__all__ = [
    "RemoteSequenceManager",
    "InferenceSession",
    "DistributedModelForCausalLM",
    "DistributedModelForSequenceClassification",
]
