"""Speculative decoding: tree math, acceptance rules, and e2e equivalence.

Ports the intent of /root/reference/tests/test_spe_dec_tree.py,
test_spec_decoding_verify.py, test_speculative_generation.py. The e2e
invariant: greedy speculative decode produces EXACTLY the tokens of plain
greedy decode.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.spec.tree import DraftTree, chain_tree, tree_attention_mask
from bloombee_tpu.spec.verify import accept_greedy, accept_sampling


def test_tree_invariants():
    #       0   1          (roots)
    #      2 3   4
    #      5
    tree = DraftTree(
        tokens=np.asarray([10, 11, 12, 13, 14, 15]),
        parents=np.asarray([-1, -1, 0, 0, 1, 2]),
    )
    assert tree.depths().tolist() == [0, 0, 1, 1, 1, 2]
    a = tree.ancestors_or_self()
    assert a[5].tolist() == [True, False, True, False, False, True]
    assert tree.path_to(5) == [0, 2, 5]
    assert tree.children_of(-1).tolist() == [0, 1]
    assert tree.children_of(0).tolist() == [2, 3]
    m = tree_attention_mask(tree)
    assert m.shape == (6, 6)
    assert not m[2, 1]  # sibling branch invisible

    with pytest.raises(ValueError):
        DraftTree(tokens=np.asarray([1, 2]), parents=np.asarray([1, -1]))

    chain = chain_tree(np.asarray([5, 6, 7]))
    assert chain.parents.tolist() == [-1, 0, 1]
    assert np.all(chain.ancestors_or_self() == np.tril(np.ones((3, 3), bool)))


def _logits_for(vocab, *winners):
    """[len(winners), vocab] logits whose argmax at row i is winners[i]."""
    out = np.zeros((len(winners), vocab), np.float32)
    for i, w in enumerate(winners):
        out[i, w] = 5.0
    return out


def test_accept_greedy_path():
    # tree: 0(tok 3) -> 1(tok 7) -> 2(tok 9); sibling 3(tok 8) under 0
    tree = DraftTree(
        tokens=np.asarray([3, 7, 9, 8]),
        parents=np.asarray([-1, 0, 1, 0]),
    )
    vocab = 16
    root_logits = _logits_for(vocab, 3)[0]  # target wants 3 -> accept node 0
    logits = _logits_for(vocab, 7, 9, 1, 0)  # node0->7, node1->9, node2->1
    accepted, bonus = accept_greedy(tree, root_logits, logits)
    assert accepted == [0, 1, 2]
    assert bonus == 1  # argmax after node 2

    # target disagrees at the root: nothing accepted, bonus = target's pick
    accepted, bonus = accept_greedy(tree, _logits_for(vocab, 5)[0], logits)
    assert accepted == [] and bonus == 5

    # target accepts node 0 then picks the sibling branch (node 3, tok 8)
    logits2 = _logits_for(vocab, 8, 9, 1, 2)  # node0 -> 8 => descend to 3
    accepted, bonus = accept_greedy(
        tree, _logits_for(vocab, 3)[0], logits2
    )
    assert accepted == [0, 3] and bonus == 2


def test_accept_sampling_peaked_matches_greedy():
    tree = DraftTree(
        tokens=np.asarray([3, 7]), parents=np.asarray([-1, 0])
    )
    vocab = 8
    root_logits = _logits_for(vocab, 3)[0] * 10
    logits = _logits_for(vocab, 7, 2)[:2] * 10
    draft_probs = np.full((2, vocab), 1e-3)
    draft_probs[0, 3] = 1.0
    draft_probs[1, 7] = 1.0
    rng = np.random.default_rng(0)
    accepted, bonus = accept_sampling(
        tree, root_logits, logits, draft_probs, rng, temperature=1.0
    )
    assert accepted == [0, 1] and bonus == 2


def test_e2e_speculative_equals_greedy(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = [
            BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=64, page_size=4),
            BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=64, page_size=4),
        ]
        for s in servers:
            await s.start()

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m", use_push=False
        )
        # the model drafts for itself -> high acceptance, exact equality
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        input_ids = np.arange(5)[None, :]
        n_new = 10

        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new
        )
        # may overshoot by the accepted path length; the generated prefix
        # must match plain greedy token-for-token
        assert spec_ids.shape[1] >= input_ids.shape[1] + n_new
        plain_ids = await model.generate(
            input_ids, max_new_tokens=spec_ids.shape[1] - input_ids.shape[1]
        )
        np.testing.assert_array_equal(spec_ids, plain_ids)

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_e2e_speculative_batch4_equals_greedy(tmp_path):
    """Batched speculative decoding (reference speculative_model.py:33-117
    per-sample trees): 4 rows with different prompts, per-row accepts, all
    token-exact vs plain batched greedy."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        servers = [
            BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=256, page_size=4),
            BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                        registry=rc(), compute_dtype=jnp.float32,
                        num_pages=256, page_size=4),
        ]
        for s in servers:
            await s.start()

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m", use_push=False
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        rng = np.random.default_rng(7)
        input_ids = rng.integers(0, 128, size=(4, 5))
        n_new = 8

        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new
        )
        assert spec_ids.shape == (4, 5 + n_new)
        plain_ids = await model.generate(input_ids, max_new_tokens=n_new)
        np.testing.assert_array_equal(spec_ids, plain_ids)

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_e2e_speculative_failover_ragged_replay(tmp_path):
    """Kill the preferred tail server between two batched speculative calls
    on one session: recovery replays RAGGED per-row token ids (rows committed
    different counts) and continuation stays token-exact vs plain greedy."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                          registry=rc(), compute_dtype=jnp.float32,
                          num_pages=256, page_size=4, throughput=10.0)
        s_b = BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                          registry=rc(), compute_dtype=jnp.float32,
                          num_pages=256, page_size=4, throughput=10.0)
        s_c = BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                          registry=rc(), compute_dtype=jnp.float32,
                          num_pages=256, page_size=4, throughput=1.0)
        for s in (s_a, s_b, s_c):
            await s.start()

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m", use_push=False
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        rng = np.random.default_rng(11)
        input_ids = rng.integers(0, 128, size=(3, 5))
        session = model.inference_session(64, 3)
        await session.__aenter__()
        used = {x.span.server_info.port for x in session._spans}
        assert s_b.port in used and s_c.port not in used

        first = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=5, session=session
        )
        # rows committed ragged counts; kill the preferred tail server
        await s_b.stop()
        more = await generate_speculative(
            model, drafter, first[:, -1:], max_new_tokens=5, session=session
        )
        await session.__aexit__(None, None, None)
        final = np.concatenate([first, more[:, 1:]], axis=1)
        plain = await model.generate(input_ids, max_new_tokens=10)
        np.testing.assert_array_equal(final, plain)

        for s in (s_a, s_c):
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_e2e_speculative_pruned_midchain(tmp_path):
    """Mid-chain pruning (reference backend.py:395-410 + client restore):
    span 0 keeps only MidLMHead survivors, downstream spans verify the
    smaller tree, the client restores kept logits to original indices —
    tokens stay exactly equal to plain greedy."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s1 = BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                         registry=rc(), compute_dtype=jnp.float32,
                         num_pages=256, page_size=4)
        s2 = BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                         registry=rc(), compute_dtype=jnp.float32,
                         num_pages=256, page_size=4)
        await s1.start()
        await s2.start()

        keeps = []
        orig_prune = s1._prune_tree

        def spy(out, prune):
            k = orig_prune(out, prune)
            keeps.append(k)
            return k

        s1._prune_tree = spy

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="m", use_push=False
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 2)
        )
        rng = np.random.default_rng(5)
        input_ids = rng.integers(0, 128, size=(2, 5))
        n_new = 8

        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new,
            prune_threshold=0.45,
        )
        assert spec_ids.shape == (2, 5 + n_new)
        plain_ids = await model.generate(input_ids, max_new_tokens=n_new)
        np.testing.assert_array_equal(spec_ids, plain_ids)
        # the pruner actually ran and dropped nodes in at least one round
        assert keeps, "server-side pruner never invoked"
        assert any(
            k is not None and (k < 0).any() for k in keeps
        ), "pruner never dropped a node (threshold too low for this test)"

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_drafter_cached_matches_uncached():
    """The prefix-KV cached drafter must build exactly the trees the
    recompute-everything path built (same top-k expansions)."""
    import jax
    import jax.numpy as jnp

    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.utils.tree import unstack_params

    spec = ModelSpec(
        family="llama", hidden_size=32, intermediate_size=64,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        num_hidden_layers=2, vocab_size=64,
    )
    blocks = [
        init_block_params(jax.random.PRNGKey(i), spec) for i in range(2)
    ]
    rng = jax.random
    client = {
        "embed": rng.normal(rng.PRNGKey(7), (64, 32)) * 0.1,
        "norm": jnp.ones((32,)),
        "lm_head": rng.normal(rng.PRNGKey(8), (32, 64)) * 0.1,
    }
    model = LocalJaxDraftModel(spec, blocks, client)
    drafter = GreedyTreeDrafter(model, branching=(2, 2, 1))
    contexts = [[1, 5, 9, 2], [3, 3, 3, 3, 3, 7]]

    trees, probs = drafter.build_batch(contexts)

    # uncached reference: full recompute per level via last_logits_ragged
    def build_uncached(ctx):
        tokens, parents = [], []
        frontier = [(-1, list(ctx))]
        for width in drafter.branching:
            seqs = [f[1] for f in frontier]
            logits = model.last_logits_ragged(seqs)
            top = np.argsort(-logits, axis=-1)[:, :width]
            new_frontier = []
            for fi, (parent, path) in enumerate(frontier):
                for tok in top[fi]:
                    idx = len(tokens)
                    tokens.append(int(tok))
                    parents.append(parent)
                    new_frontier.append((idx, path + [int(tok)]))
            frontier = new_frontier
        return tokens, parents

    # numerical agreement first (the robust contract: cached and uncached
    # attention reduce in different orders, so logits match to tolerance)
    l_cached = model.prefill_ragged(contexts)[2]
    l_uncached = model.last_logits_ragged(contexts)
    np.testing.assert_allclose(l_cached, l_uncached, atol=1e-4, rtol=1e-4)
    for r, ctx in enumerate(contexts):
        ref_tokens, ref_parents = build_uncached(ctx)
        np.testing.assert_array_equal(trees[r].tokens, ref_tokens)
        np.testing.assert_array_equal(trees[r].parents, ref_parents)


def test_shape_chooser_prefers_depth_when_accepts_are_high():
    from bloombee_tpu.spec.shape import (
        AcceptanceStats,
        choose_branching,
        expected_accepted,
        tree_nodes,
    )

    assert tree_nodes((2, 2, 1)) == 11

    hot = AcceptanceStats()
    cold = AcceptanceStats()
    for _ in range(200):
        hot.observe(3, (2, 2, 2))   # everything accepts
        cold.observe(0, (2, 2, 2))  # nothing ever accepts
    deep, shallow = (2, 2, 2), (4,)
    assert expected_accepted(deep, hot) > expected_accepted(shallow, hot)
    chosen_hot = choose_branching(hot, budget_nodes=15)
    chosen_cold = choose_branching(cold, budget_nodes=15)
    assert len(chosen_hot) >= 2  # deep pays off when accepts are high
    assert tree_nodes(chosen_cold) <= tree_nodes(chosen_hot)


def test_e2e_adaptive_drafter_stays_exact(tmp_path):
    """Adaptive tree shaping retunes branching mid-generation; tokens must
    stay exactly greedy."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=256,
                        page_size=4)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 2),
            adaptive=True, retune_every=2,
        )
        input_ids = np.arange(5)[None, :]
        n_new = 14
        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new
        )
        plain_ids = await model.generate(input_ids, max_new_tokens=n_new)
        np.testing.assert_array_equal(spec_ids, plain_ids)
        assert drafter.stats.tries.sum() > 0  # feedback actually flowed
        await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_e2e_speculative_sampling(tmp_path):
    """Sampling-mode speculative decode (SpecInfer rejection sampling): at
    near-zero temperature it equals greedy; at temperature 1 it runs and is
    reproducible per seed."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = BlockServer(model_uid="m", start=0, end=3, model_dir=d,
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=256,
                        page_size=4)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        input_ids = np.arange(2 * 5).reshape(2, 5) % 120
        n_new = 6

        cold = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new,
            do_sample=True, temperature=1e-4, seed=0,
        )
        greedy = await model.generate(input_ids, max_new_tokens=n_new)
        np.testing.assert_array_equal(cold, greedy)

        hot1 = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new,
            do_sample=True, temperature=1.0, seed=7,
        )
        hot2 = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=n_new,
            do_sample=True, temperature=1.0, seed=7,
        )
        assert hot1.shape == (2, 5 + n_new)
        np.testing.assert_array_equal(hot1, hot2)  # seed-reproducible

        await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_accept_sampling_preserves_target_distribution():
    """The emitted token (accepted draft or bonus) must be distributed
    exactly as softmax(target/T), with DETERMINISTIC top-k proposals — the
    way our drafter actually proposes (the SpecInfer min(1,p/q) rule would
    be biased here)."""
    from bloombee_tpu.spec.verify import _softmax

    vocab = 6
    rng0 = np.random.default_rng(42)
    target_logits = rng0.normal(size=vocab) * 1.5
    drafter_logits = rng0.normal(size=vocab) * 1.5
    top2 = np.argsort(-drafter_logits)[:2]  # deterministic proposals
    for temperature in (1.0, 0.5):
        counts = np.zeros(vocab)
        n = 40000
        rng = np.random.default_rng(0)
        tree = DraftTree(
            tokens=np.asarray(top2), parents=np.asarray([-1, -1])
        )
        dummy = np.zeros((2, vocab), np.float32)
        for _ in range(n):
            accepted, bonus = accept_sampling(
                tree, target_logits, dummy, _softmax(drafter_logits[None]),
                rng, temperature=temperature,
            )
            tok = int(tree.tokens[accepted[0]]) if accepted else bonus
            counts[tok] += 1
        emp = counts / n
        tgt = _softmax(target_logits[None] / temperature)[0]
        tv = 0.5 * np.abs(emp - tgt).sum()
        assert tv < 0.02, (temperature, tv, emp.round(3), tgt.round(3))


def test_e2e_speculative_qwen2_family(tmp_path):
    """Non-llama family drafting + tree-verifying through the swarm: the
    drafter registry is family-generic (round-4 verdict: it hardwired
    llama's block_forward). Qwen2 brings biased qkv projections."""
    import transformers as tf

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = tf.Qwen2Config(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(4)
    hf = tf.Qwen2ForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "qwen2")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="q", start=0, end=2, model_dir=d, registry=rc(),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
        )
        await server.start()

        model = DistributedModelForCausalLM.from_pretrained(
            d, rc(), model_uid="q", use_push=False
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 1)
        )
        input_ids = np.arange(5)[None, :]
        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=8
        )
        assert spec_ids.shape[1] >= input_ids.shape[1] + 8
        plain_ids = await model.generate(
            input_ids, max_new_tokens=spec_ids.shape[1] - input_ids.shape[1]
        )
        np.testing.assert_array_equal(spec_ids, plain_ids)

        await server.stop()
        await reg.stop()

    asyncio.run(run())


def test_drafter_rejects_unsupported_family():
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.spec.drafter import LocalJaxDraftModel

    spec = ModelSpec(
        family="bloom", hidden_size=32, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=4, head_dim=8,
        num_hidden_layers=2, vocab_size=64, alibi=True, norm_type="ln",
        mlp_type="gelu_tanh",
    )
    with pytest.raises(NotImplementedError, match="ALiBi"):
        LocalJaxDraftModel(spec, [], {})
