"""Llama family config mapping (reference: models/llama/config.py:16-19,
flexgen_utils/llama_config.py)."""

from __future__ import annotations

from typing import Any

from bloombee_tpu.models.spec import ModelSpec


def llama_spec_from_hf(config: Any) -> ModelSpec:
    head_dim = getattr(config, "head_dim", None) or (
        config.hidden_size // config.num_attention_heads
    )
    return ModelSpec(
        family="llama",
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        num_attention_heads=config.num_attention_heads,
        num_key_value_heads=getattr(
            config, "num_key_value_heads", config.num_attention_heads
        ),
        head_dim=head_dim,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=config.rms_norm_eps,
        rope_theta=getattr(config, "rope_theta", 10000.0),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", False),
        max_position_embeddings=getattr(config, "max_position_embeddings", 4096),
    )
