"""Server-side draft-tree pruning (MidLMHead + probability pruner).

Port of /root/reference/src/bloombee/server/speculative_pruner/
(pruner_manager.py:13-186, simple_probability_pruner.py:11-241,
mid_layer_LM_head.py): a small trainable linear head scores MID-network
hidden states of draft-tree nodes; children whose renormalized
parent-conditioned probability clears a threshold are kept, the rest are
pruned before the remaining (deeper) blocks run — cutting wasted tree
compute and downstream wire bytes.

This module provides the jitted scoring head and the keep-index math with
the reference's semantics (keep_indices padded with -1, parents always kept
when any descendant survives). Wire integration (shrinking the tree
mid-chain) lands with the micro-batch/multiplexing work.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.spec.tree import DraftTree


class MidLMHead:
    """Small linear head over mid-network hidden states (trainable online in
    the reference via lm_head_trainer; here initialized from the real LM
    head or randomly and updatable by assignment). An optional RMS norm
    weight is applied first ("logit lens"): raw mid-layer hidden has a
    growing scale that makes untrained-head softmaxes uninformative."""

    def __init__(self, weight: jax.Array, norm=None, eps: float = 1e-5):
        self.weight = weight  # [D, V]
        self.norm = norm  # [D] or None
        self.eps = eps

    @staticmethod
    @jax.jit
    def _probs(weight, norm, eps, hidden):
        if norm is not None:
            from bloombee_tpu.ops import rms_norm

            hidden = rms_norm(hidden, norm, eps)
        logits = (hidden @ weight).astype(jnp.float32)
        return jax.nn.softmax(logits, axis=-1)

    def probs(self, hidden: np.ndarray) -> np.ndarray:
        """hidden [N, D] -> softmax rows [N, V]; per-token gathering against
        the parent's distribution happens in the pruner."""
        return np.asarray(
            self._probs(self.weight, self.norm, self.eps, jnp.asarray(hidden))
        )


@dataclasses.dataclass
class SimpleProbabilityPruner:
    """Keep children whose parent-conditioned renormalized probability
    clears `threshold` (reference simple_probability_pruner.py)."""

    threshold: float = 0.05
    max_keep: int | None = None

    def keep_indices(
        self,
        tree: DraftTree,
        probs: np.ndarray,  # [T+1?, V]: row 0.. per node position; row for
        # the root level comes from the last committed token (index -1 via
        # `root_probs`)
        root_probs: np.ndarray,  # [V]
    ) -> np.ndarray:
        """Returns kept linear indices, padded with -1 to max_keep (or tree
        size). A node is kept iff its own conditional prob clears the
        threshold AND its parent is kept (subtree pruning)."""
        t = tree.size
        keep = np.zeros(t, dtype=bool)
        # renormalize within each sibling group
        for parent in [-1] + list(range(t)):
            children = tree.children_of(parent)
            if len(children) == 0:
                continue
            dist = root_probs if parent < 0 else probs[parent]
            child_p = np.asarray(
                [dist[int(tree.tokens[c])] for c in children], np.float64
            )
            z = child_p.sum()
            if z <= 0:
                continue
            child_p = child_p / z
            for c, p in zip(children, child_p):
                parent_ok = parent < 0 or keep[parent]
                keep[c] = parent_ok and (p >= self.threshold)
        kept = np.nonzero(keep)[0]
        cap = self.max_keep or t
        if len(kept) > cap:
            kept = kept[:cap]
        out = np.full(cap, -1, dtype=np.int32)
        out[: len(kept)] = kept
        return out


class PrunerManager:
    """Lazy-init + method dispatch (reference pruner_manager.py): owns the
    MidLMHead and the active pruning strategy."""

    def __init__(self, threshold: float = 0.05):
        self._head: MidLMHead | None = None
        self._pruner = SimpleProbabilityPruner(threshold=threshold)

    def ensure_head(
        self, lm_head_weight, norm=None, eps: float = 1e-5
    ) -> MidLMHead:
        if self._head is None:
            self._head = MidLMHead(
                jnp.asarray(lm_head_weight),
                None if norm is None else jnp.asarray(norm),
                eps,
            )
        return self._head

    def prune(
        self,
        tree: DraftTree,
        hidden: np.ndarray,  # [T, D] mid-network hidden states of the nodes
        root_hidden: np.ndarray,  # [D] last committed token's hidden
        lm_head_weight,
    ) -> np.ndarray:
        head = self.ensure_head(lm_head_weight)
        all_rows = head.probs(
            np.concatenate([root_hidden[None], hidden], axis=0)
        )
        return self._pruner.keep_indices(tree, all_rows[1:], all_rows[0])
