"""Megatron-style SPMD block compute under shard_map (tp + sp + dp).

Replaces the reference's intra-host tensor parallelism
(/root/reference/src/bloombee/server/flexgen_tensor_parallel.py:172-828:
per-device CUDA streams, row/col weight slices, stream all-reduce) with the
TPU idiom: weights sharded over the "tp" mesh axis, local matmuls on each
shard, one psum over ICI after o_proj and down_proj. Attention runs as ring
attention over the "sp" axis, so long sequences scale across the mesh instead
of offloading to host.

All functions here execute INSIDE shard_map (they use axis primitives);
`shard_span_params` prepares the NamedSharding placement that makes shard_map
hand each device its local shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops import rms_norm, silu_mlp
from bloombee_tpu.ops.rotary import apply_rotary, rotary_cos_sin
from bloombee_tpu.parallel.ring_attention import ring_attention

# PartitionSpecs for stacked span params [L, ...]; layer dim shards over pp
PARAM_SPECS = {
    "input_layernorm": P("pp", None),
    "input_layernorm_bias": P("pp", None),
    "post_attention_layernorm": P("pp", None),
    "post_attention_layernorm_bias": P("pp", None),
    "mlp_layernorm": P("pp", None),  # falcon new-arch dual-LN
    "mlp_layernorm_bias": P("pp", None),
    "pre_feedforward_layernorm": P("pp", None),  # gemma2 sandwich
    "post_feedforward_layernorm": P("pp", None),
    "q_proj": P("pp", None, "tp"),
    "k_proj": P("pp", None, "tp"),
    "v_proj": P("pp", None, "tp"),
    "o_proj": P("pp", "tp", None),
    # qkv biases shard with their projection's OUTPUT dim, so they add
    # shard-locally before any psum (qwen2-style biased attention)
    "q_bias": P("pp", "tp"),
    "k_bias": P("pp", "tp"),
    "v_bias": P("pp", "tp"),
    "gate_proj": P("pp", None, "tp"),
    "up_proj": P("pp", None, "tp"),
    "down_proj": P("pp", "tp", None),
    "q_norm": P("pp", None),
    "k_norm": P("pp", None),
    # MoE (mixtral): experts shard over the tp axis = expert parallelism,
    # which the reference lacks entirely (SURVEY.md section 2.8)
    "router": P("pp", None, None),
    "experts_gate": P("pp", "tp", None, None),
    "experts_up": P("pp", "tp", None, None),
    "experts_down": P("pp", "tp", None, None),
}


def _check_known_keys(params: dict) -> None:
    unknown = sorted(set(params) - set(PARAM_SPECS))
    if unknown:
        # loud, named failure instead of a raw KeyError: these are the
        # same exclusions _spmd_unsupported documents (row-parallel
        # biases / exotic families)
        raise NotImplementedError(
            f"SPMD path has no sharding specs for params {unknown} "
            "(row-parallel biases and this family's extras aren't "
            "supported here yet)"
        )


def param_specs(params: dict) -> dict:
    _check_known_keys(params)
    return {k: PARAM_SPECS[k] for k in params}


def shard_span_params(params: dict, mesh: Mesh) -> dict:
    """Place stacked span params on the mesh (pp over layers, tp over
    heads/ffn)."""
    _check_known_keys(params)
    return {
        k: jax.device_put(v, NamedSharding(mesh, PARAM_SPECS[k]))
        for k, v in params.items()
    }


def _spmd_unsupported(spec: ModelSpec, params_l: dict) -> str | None:
    """Why this family cannot run the SPMD training body; None when it
    can. The remaining exclusions are RING-ATTENTION limits (no sliding
    window, no ALiBi positional bias, no logit soft-cap) plus row-parallel
    output biases — everything else routes through the same spec switches
    as the serving layer_body."""
    if spec.layer_types and "sliding" in spec.layer_types:
        return (
            "ring attention is full-causal; sliding-window families "
            "(mistral/gemma) aren't supported here yet"
        )
    if spec.alibi:
        return "ring attention has no positional-bias (ALiBi) path yet"
    if spec.attn_logit_softcap:
        return "ring attention has no logit soft-cap path yet"
    if spec.heterogeneous:
        return "heterogeneous head_dim spans don't stack into one scan"
    if any(
        k in params_l
        for k in ("o_bias", "down_bias", "gate_bias", "up_bias")
    ):
        # row-parallel biases would be added once per shard before the
        # psum; no in-scope family carries them (bloom does, but ALiBi
        # already excludes it)
        return "row-parallel projection biases aren't supported here yet"
    return None


def spmd_block_forward(
    params_l: dict,  # one layer's LOCAL param shards
    hidden: jax.Array,  # [b_local, C, D] (dp-sharded batch, sp-sharded seq)
    *,
    spec: ModelSpec,
    sp_axis: str = "sp",
    tp_axis: str = "tp",
    return_kv: bool = False,  # also return this layer's LOCAL (k, v)
    # chunk shards [b, C, kv_local, hd] — the sp-serving prefill collects
    # them into the paged arena so decode can continue single-chip
):
    """Family-generic SPMD layer: the same ModelSpec switches as the
    serving layer_body (norm type + biases, parallel-attn residual,
    sandwich norms, gelu/silu/MoE MLPs, qk-norm, qkv biases) over ring
    attention + Megatron psums. Covers llama/qwen2/qwen3/mixtral/falcon;
    `_spmd_unsupported` lists what still fails loudly."""
    from bloombee_tpu.runtime.layer_body import _norm, attn_scale

    b, c, d = hidden.shape
    reason = _spmd_unsupported(spec, params_l)
    if reason is not None:
        raise NotImplementedError(
            f"spmd block body doesn't cover family {spec.family!r}: {reason}"
        )
    tp = lax.axis_size(tp_axis)
    if spec.num_attention_heads % tp or spec.num_key_value_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_attention_heads="
            f"{spec.num_attention_heads} and num_key_value_heads="
            f"{spec.num_key_value_heads} (KV-head replication not yet "
            "implemented)"
        )
    h_local = spec.num_attention_heads // tp
    kv_local = spec.num_key_value_heads // tp
    hd = spec.head_dim

    sp_rank = lax.axis_index(sp_axis)
    positions = sp_rank * c + jnp.arange(c)
    positions = jnp.broadcast_to(positions[None], (b, c))
    cos, sin = rotary_cos_sin(positions, hd, spec.rope_theta)
    cos = cos.astype(hidden.dtype)
    sin = sin.astype(hidden.dtype)

    def col(x, key):
        # column-parallel projection: output dim sharded, so the bias
        # shard adds locally (before any reduction)
        y = x @ params_l[key]
        bias = params_l.get(f"{key.removesuffix('_proj')}_bias")
        if bias is not None:
            y = y + bias
        return y

    x = _norm(hidden, params_l, "input_layernorm", spec)
    q = col(x, "q_proj").reshape(b, c, h_local, hd)
    k = col(x, "k_proj").reshape(b, c, kv_local, hd)
    v = col(x, "v_proj").reshape(b, c, kv_local, hd)
    if spec.qk_norm:
        q = rms_norm(q, params_l["q_norm"], spec.rms_norm_eps)
        k = rms_norm(k, params_l["k_norm"], spec.rms_norm_eps)
    q, k = apply_rotary(q, k, cos, sin)

    attn = ring_attention(
        q, k, v, axis_name=sp_axis, causal=True, scale=attn_scale(spec)
    )
    partial = attn.reshape(b, c, h_local * hd) @ params_l["o_proj"]
    attn_out = lax.psum(partial, tp_axis)

    def mlp_partial(x):
        """LOCAL MLP contribution (intermediate dim sharded); the caller
        psums. Same spec switches as layer_body._mlp, bias-free (checked
        in _spmd_unsupported)."""
        if spec.num_experts:
            # expert parallelism: full router everywhere, local expert
            # shard computes its weighted contribution, psum combines
            from bloombee_tpu.ops.moe import moe_mlp, router_topk_weights

            weights = router_topk_weights(
                x @ params_l["router"], spec.num_experts_per_tok,
                pre_softmax=spec.moe_pre_softmax,
                norm_topk=spec.moe_norm_topk,
            )  # [b, c, E] full
            e_local = params_l["experts_gate"].shape[0]
            rank = lax.axis_index(tp_axis)
            local_w = lax.dynamic_slice_in_dim(
                weights, rank * e_local, e_local, axis=-1
            )
            return moe_mlp(
                x, None, params_l["experts_gate"], params_l["experts_up"],
                params_l["experts_down"], spec.num_experts_per_tok,
                router_weights=local_w,
            )
        if spec.mlp_type == "silu":
            return silu_mlp(
                x, params_l["gate_proj"], params_l["up_proj"],
                params_l["down_proj"],
            )
        if spec.mlp_type == "gelu_tanh_gated":
            g = jax.nn.gelu(x @ params_l["gate_proj"], approximate=True)
            return (g * (x @ params_l["up_proj"])) @ params_l["down_proj"]
        # plain 4h GELU ("gelu" = exact/erf for falcon)
        h = jax.nn.gelu(
            x @ params_l["up_proj"], approximate=spec.mlp_type != "gelu"
        )
        return h @ params_l["down_proj"]

    if spec.parallel_attn:
        # falcon: parallel attention+MLP residual; new-arch uses a second
        # LN for the MLP branch, 7b shares the input norm
        if spec.num_ln_in_parallel_attn == 2:
            x_mlp = _norm(hidden, params_l, "mlp_layernorm", spec)
        else:
            x_mlp = x
        out = hidden + attn_out + lax.psum(mlp_partial(x_mlp), tp_axis)
    elif spec.sandwich_norms:
        attn_out = _norm(attn_out, params_l, "post_attention_layernorm", spec)
        hidden = hidden + attn_out
        x2 = _norm(hidden, params_l, "pre_feedforward_layernorm", spec)
        mlp_out = lax.psum(mlp_partial(x2), tp_axis)
        mlp_out = _norm(mlp_out, params_l, "post_feedforward_layernorm", spec)
        out = hidden + mlp_out
    else:
        hidden = hidden + attn_out
        x2 = _norm(hidden, params_l, "post_attention_layernorm", spec)
        out = hidden + lax.psum(mlp_partial(x2), tp_axis)
    if return_kv:
        return out, (k, v)
    return out


def spmd_span_forward(
    stacked_local: dict,  # local param shards with leading local-layer dim
    hidden: jax.Array,
    *,
    spec: ModelSpec,
    sp_axis: str = "sp",
    tp_axis: str = "tp",
) -> jax.Array:
    def body(h, params_l):
        return (
            spmd_block_forward(
                params_l, h, spec=spec, sp_axis=sp_axis, tp_axis=tp_axis
            ),
            None,
        )

    hidden, _ = lax.scan(body, hidden, stacked_local)
    return hidden


def spmd_span_forward_kv(
    stacked_local: dict,
    hidden: jax.Array,
    *,
    spec: ModelSpec,
    sp_axis: str = "sp",
    tp_axis: str = "tp",
):
    """spmd_span_forward that also stacks every layer's local (k, v)
    chunk shards [L, b, C, kv_local, hd] — the sp-serving prefill writes
    them into the paged arena so DECODE continues on the ordinary
    single-chip paged path."""

    def body(h, params_l):
        h, (k, v) = spmd_block_forward(
            params_l, h, spec=spec, sp_axis=sp_axis, tp_axis=tp_axis,
            return_kv=True,
        )
        return h, (k, v)

    hidden, (ks, vs) = lax.scan(body, hidden, stacked_local)
    return hidden, ks, vs
