"""Chaos gate: scripts/chaos.sh must pass as part of the tier-1 suite.

The script replays every chaos-marked test under a fixed BBTPU_CHAOS_*
seed matrix (ambient wire jitter on top of the tests' own seeded fault
plans), so fault-recovery paths are exercised with injected noise on
every run — not only when an operator remembers to soak them. It exits 0
when pytest is unavailable, mirroring the scripts/lint.sh contract.
"""

import pathlib
import subprocess

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_chaos_suite_under_seed_matrix():
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "chaos.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=580,
    )
    assert proc.returncode == 0, (
        f"chaos regressions:\n{proc.stdout[-8000:]}\n{proc.stderr[-4000:]}"
    )
