"""Wire plane: tensor serialization, lossless compression, async RPC.

Replaces the reference's transport stack — hivemind libp2p streams + protobuf
ExpertRequest/Response + the lossless_transport wrapper
(/root/reference/src/bloombee/utils/lossless_transport.py, SURVEY.md section
2.7). The capability seams are kept (unary + bidirectional streaming RPC,
server->server push, compressed tensor frames with MSGPack metadata); the
implementation is a length-prefixed msgpack framing over asyncio TCP, which a
TPU-VM swarm reaches over DCN.
"""

from bloombee_tpu.wire.tensor_codec import (
    serialize_tensor,
    deserialize_tensor,
    serialize_tensors,
    deserialize_tensors,
    register_codec,
    supported_codecs,
    LEGACY_WIRE_CODECS,
)
from bloombee_tpu.wire.pipeline import CodecPipeline
from bloombee_tpu.wire.rpc import Connection, RpcServer, RpcError, connect

__all__ = [
    "serialize_tensor",
    "deserialize_tensor",
    "serialize_tensors",
    "deserialize_tensors",
    "register_codec",
    "supported_codecs",
    "LEGACY_WIRE_CODECS",
    "CodecPipeline",
    "Connection",
    "RpcServer",
    "RpcError",
    "connect",
]
