"""Paged KV table: host-side control plane.

Ports the *invariants* of the reference's PagedKVTable
(/root/reference/src/bloombee/server/paged_kv.py:52-317): page-granular
allocation (default page size 16, :35), per-sequence page lists, committed
length `l_acc` vs speculative length `l_seq`, `commit`/`rollback` freeing
orphaned pages (:235-261), and prefix reads clamped to `l_acc` (:265-316).

The design differs from the reference in one deliberate way: this table never
touches tensors. The reference's `write` moves KV bytes page-at-a-time into a
torch slab (:137-204); here the table only *assigns slots* —
`assign_write_slots` returns flat arena slot indices that the jitted device
step scatters into (see bloombee_tpu/kv/arena.py). The reference's
`track_write` state-only mirror (:206-231) is therefore the native operation.
"""

from __future__ import annotations

import collections
import dataclasses

import numpy as np

DEFAULT_PAGE_SIZE = 16


class OutOfPages(RuntimeError):
    """Raised when the arena has no free pages for a reservation."""


@dataclasses.dataclass
class SeqState:
    pages: list[int]
    l_acc: int = 0  # committed token count
    l_seq: int = 0  # total written (committed + speculative)
    # prefix-cache identity: chained page hashes of this sequence's prompt
    # (kv/prefix.py) and how many leading pages have been offered to the
    # shared pool so far (publication is monotone per seq, clamped when the
    # committed prefix shrinks)
    hashes: list[str] | None = None
    published: int = 0

    @property
    def num_pages(self) -> int:
        return len(self.pages)


class PagedKVTable:
    """Page allocator + per-sequence length bookkeeping (host side).

    Prefix-cache extension (vLLM block sharing + SGLang-style reuse): every
    page carries a refcount; fully-committed pages whose content hash is
    known are *published* into a hash-indexed pool. When the last reference
    drops, a published page parks in an LRU of reclaimable cached pages
    instead of the free list — a later sequence whose prompt chain matches
    adopts it (refcount back up, prefill skipped), while allocation pressure
    evicts from the LRU's cold end. A write into a page that is shared
    (ref > 1) or still advertised in the pool triggers copy-on-write: the
    writer gets a fresh page and the (src, dst) pair is queued for the
    device-side page copy (drained by CacheManager before the step's
    scatter lands).

    Concurrency contract (enforced by bbtpu-lint BB002/BB003, see
    ARCHITECTURE.md "Invariants"): the table carries NO lock of its own —
    every mutation happens under CacheManager's RLock (its `@_locked`
    methods) on the compute thread. If this class ever grows a lock, it
    sits at level 1 of the declared hierarchy
    cache_manager -> paged table -> compute queue: it may be taken while
    holding the manager's lock, never the reverse.
    """

    def __init__(self, num_pages: int, page_size: int = DEFAULT_PAGE_SIZE):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._seqs: dict[int, SeqState] = {}
        # prefix-cache state. _pool and _page_hash are exact inverses:
        # _pool[h] == p  <=>  _page_hash[p] == h. _lru holds refcount-0
        # published pages, oldest-released first (eviction order).
        self._ref: list[int] = [0] * num_pages
        self._pool: dict[str, int] = {}
        self._page_hash: dict[int, str] = {}
        self._lru: collections.OrderedDict[int, None] = (
            collections.OrderedDict()
        )
        self._pending_copies: list[tuple[int, int]] = []
        self.cow_count = 0
        # optional cap on the cached pool (BBTPU_PREFIX_MAX_PAGES); 0 = no
        # cap beyond what allocation pressure evicts naturally
        self.max_cached_pages = 0

    # ------------------------------------------------------------- lifecycle
    @property
    def free_pages(self) -> int:
        """Allocatable pages: truly free + reclaimable cached (LRU)."""
        return len(self._free) + len(self._lru)

    @property
    def free_tokens(self) -> int:
        return self.free_pages * self.page_size

    @property
    def cached_pages(self) -> int:
        """Refcount-0 pages held in the prefix pool (LRU-evictable)."""
        return len(self._lru)

    def has_seq(self, seq_id: int) -> bool:
        return seq_id in self._seqs

    def seq(self, seq_id: int) -> SeqState:
        return self._seqs[seq_id]

    def add_seq(self, seq_id: int) -> None:
        if seq_id in self._seqs:
            raise ValueError(f"sequence {seq_id} already exists")
        self._seqs[seq_id] = SeqState(pages=[])

    def drop_seq(self, seq_id: int) -> None:
        state = self._seqs.pop(seq_id)
        for page in state.pages:
            self._release_page(page)

    # ------------------------------------------------------------ allocation
    def _pages_for(self, tokens: int) -> int:
        return -(-tokens // self.page_size)

    def _alloc_page(self) -> int:
        """One refcount-1 page: free list first, else evict the coldest
        cached page (de-publishing it — its content is about to be
        overwritten)."""
        if self._free:
            page = self._free.pop()
        elif self._lru:
            page, _ = self._lru.popitem(last=False)
            self._unpublish(page)
        else:
            raise OutOfPages("no free or cached pages left")
        self._ref[page] = 1
        return page

    def _release_page(self, page: int) -> None:
        """Drop one reference; at zero, published pages park in the cached
        LRU (warm for future adoption), unpublished pages free."""
        self._ref[page] -= 1
        assert self._ref[page] >= 0, f"page {page} refcount underflow"
        if self._ref[page] > 0:
            return
        if page in self._page_hash:
            self._lru[page] = None
            self._lru.move_to_end(page)
            if self.max_cached_pages > 0:
                while len(self._lru) > self.max_cached_pages:
                    cold, _ = self._lru.popitem(last=False)
                    self._unpublish(cold)
                    self._free.append(cold)
        else:
            self._free.append(page)

    def _unpublish(self, page: int) -> None:
        h = self._page_hash.pop(page, None)
        if h is not None:
            del self._pool[h]

    def reserve(self, seq_id: int, new_total_len: int) -> None:
        """Grow the sequence's page list to cover `new_total_len` tokens."""
        state = self._seqs[seq_id]
        need = self._pages_for(new_total_len) - len(state.pages)
        if need <= 0:
            return
        if need > self.free_pages:
            raise OutOfPages(
                f"need {need} pages, only {self.free_pages} free"
            )
        for _ in range(need):
            state.pages.append(self._alloc_page())

    # --------------------------------------------------------------- writing
    def assign_write_slots(
        self, seq_id: int, num_tokens: int, commit: bool = True
    ) -> np.ndarray:
        """Assign flat arena slots for the next `num_tokens` tokens.

        Tokens land at positions [l_seq, l_seq + num_tokens); reserves pages
        as needed. `commit=False` marks them speculative (rollback-able),
        mirroring the reference write(commit=...) flag (paged_kv.py:137-204).
        Returns int32 flat slot ids (page * page_size + offset).
        """
        if num_tokens < 0:
            raise ValueError(f"num_tokens must be >= 0, got {num_tokens}")
        state = self._seqs[seq_id]
        start = state.l_seq
        if commit and state.l_acc != start:
            # validate BEFORE reserving: an invalid write must not mutate
            # the table (pages/lengths) on its way to the exception
            raise ValueError(
                "committed write must follow the committed prefix "
                f"(l_acc={state.l_acc}, write starts at {start})"
            )
        # copy-on-write: a write landing in a page that is shared (ref > 1)
        # or still advertised in the prefix pool must not mutate the shared
        # bytes — swap in a private copy first. Checked against the full
        # availability (reserve need + cow need) so a mid-batch OutOfPages
        # cannot leave the sequence half-diverged.
        cow_idx: list[int] = []
        if num_tokens > 0 and state.pages:
            first = start // self.page_size
            last = (start + num_tokens - 1) // self.page_size
            for i in range(first, min(last + 1, len(state.pages))):
                page = state.pages[i]
                if self._ref[page] > 1 or page in self._page_hash:
                    cow_idx.append(i)
        need = max(
            0, self._pages_for(start + num_tokens) - len(state.pages)
        )
        if need + len(cow_idx) > self.free_pages:
            raise OutOfPages(
                f"need {need + len(cow_idx)} pages "
                f"({len(cow_idx)} copy-on-write), only "
                f"{self.free_pages} free"
            )
        for i in cow_idx:
            src = state.pages[i]
            dst = self._alloc_page()
            self._pending_copies.append((src, dst))
            state.pages[i] = dst
            self._release_page(src)
            self.cow_count += 1
            # the diverged copy no longer matches the hash chain from this
            # page on: truncate so it can never be (re)published stale
            if state.hashes is not None and i < len(state.hashes):
                state.hashes = state.hashes[:i]
            state.published = min(state.published, i)
        self.reserve(seq_id, start + num_tokens)
        positions = np.arange(start, start + num_tokens)
        pages = np.asarray(state.pages, dtype=np.int64)[
            positions // self.page_size
        ]
        slots = pages * self.page_size + positions % self.page_size
        state.l_seq = start + num_tokens
        if commit:
            state.l_acc = state.l_seq
            self._publish(state)
        return slots.astype(np.int32)

    # ------------------------------------------------------ commit / rollback
    def commit(self, seq_id: int, length: int | None = None) -> None:
        """Promote speculative tokens to committed; free pages past the end.

        `length` defaults to l_seq (commit everything written). Mirrors
        paged_kv.py:235-246.
        """
        state = self._seqs[seq_id]
        if length is None:
            length = state.l_seq
        if not (state.l_acc <= length <= state.l_seq):
            raise ValueError(
                f"commit length {length} outside [{state.l_acc}, {state.l_seq}]"
            )
        state.l_acc = length
        state.l_seq = length
        self._trim(state)
        self._publish(state)

    def accept(self, seq_id: int, num_accepted: int) -> None:
        """Keep the first `num_accepted` speculative tokens (after the caller
        compacted the arena rows onto them) and discard the rest."""
        state = self._seqs[seq_id]
        if not 0 <= num_accepted <= state.l_seq - state.l_acc:
            raise ValueError(
                f"accept {num_accepted} outside speculative window "
                f"[0, {state.l_seq - state.l_acc}]"
            )
        state.l_acc += num_accepted
        state.l_seq = state.l_acc
        self._trim(state)
        self._publish(state)

    def range_slots(self, seq_id: int, start: int, end: int) -> np.ndarray:
        """Flat slot ids for positions [start, end) (must be materialized)."""
        state = self._seqs[seq_id]
        if end > len(state.pages) * self.page_size:
            raise ValueError("range beyond allocated pages")
        positions = np.arange(start, end)
        pages = np.asarray(state.pages, dtype=np.int64)[
            positions // self.page_size
        ]
        return (pages * self.page_size + positions % self.page_size).astype(
            np.int32
        )

    def rollback(self, seq_id: int) -> None:
        """Discard speculative tokens; free orphaned pages
        (paged_kv.py:247-261)."""
        state = self._seqs[seq_id]
        state.l_seq = state.l_acc
        self._trim(state)

    def truncate_speculative(self, seq_id: int, length: int) -> None:
        """Partial rollback: drop speculative tokens past `length` but keep
        the ones below it. A failed dispatch stacked atop EARLIER
        still-speculative tokens (a mid-stream prefill chunk in a mixed
        batch) must undo only its own writes — a full rollback() would
        discard the earlier chunks too."""
        state = self._seqs[seq_id]
        if not state.l_acc <= length <= state.l_seq:
            raise ValueError(
                f"truncate length {length} outside "
                f"[{state.l_acc}, {state.l_seq}]"
            )
        state.l_seq = length
        self._trim(state)

    def reset_seq(self, seq_id: int) -> None:
        """Drop ALL tokens (committed included) and free the pages, keeping
        the sequence registered — the parking primitive."""
        state = self._seqs[seq_id]
        state.l_acc = 0
        state.l_seq = 0
        self._trim(state)

    def restore_committed(self, seq_id: int, l_acc: int) -> None:
        """Set the committed watermark without touching l_seq (unparking
        re-materializes tokens speculatively, then restores l_acc)."""
        state = self._seqs[seq_id]
        if not 0 <= l_acc <= state.l_seq:
            raise ValueError(
                f"l_acc {l_acc} outside [0, {state.l_seq}]"
            )
        state.l_acc = l_acc
        self._publish(state)

    def _trim(self, state: SeqState) -> None:
        keep = self._pages_for(max(state.l_seq, state.l_acc))
        while len(state.pages) > keep:
            self._release_page(state.pages.pop())
        state.published = min(
            state.published, state.l_acc // self.page_size
        )

    # ---------------------------------------------------------- prefix cache
    def set_seq_hashes(self, seq_id: int, hashes: list[str]) -> None:
        """Attach the prompt's page-hash chain (kv/prefix.py) so this
        sequence's fully-committed prompt pages get published to the pool
        as they commit."""
        self._seqs[seq_id].hashes = list(hashes)

    def _publish(self, state: SeqState) -> None:
        """Offer newly fully-committed hash-covered pages to the pool.

        A hash already pooled (another copy of the same content) is skipped
        — the pool keeps one canonical page per chain hash. `published` is
        monotone per call so retried commits don't re-offer."""
        if state.hashes is None:
            return
        limit = min(state.l_acc // self.page_size, len(state.hashes))
        for i in range(state.published, limit):
            h = state.hashes[i]
            page = state.pages[i]
            if h not in self._pool and page not in self._page_hash:
                self._pool[h] = page
                self._page_hash[page] = h
        state.published = max(state.published, limit)

    def match_prefix(self, hashes: list[str]) -> int:
        """Tokens of the chain currently servable from the pool (a probe —
        no state change; adoption may still race an eviction)."""
        n = 0
        for h in hashes:
            if h not in self._pool:
                break
            n += 1
        return n * self.page_size

    def adopt_prefix(
        self, seq_id: int, hashes: list[str], max_tokens: int | None = None
    ) -> int:
        """Map the longest pooled prefix of `hashes` into an EMPTY sequence.

        Adopted pages are refcounted up (pulled out of the LRU — pinned
        against eviction until released) and the sequence starts life with
        a committed prefix of the returned token count. The chain is kept so
        pages this sequence computes itself get published in turn.
        """
        state = self._seqs[seq_id]
        if state.pages or state.l_seq or state.l_acc:
            raise ValueError("adopt_prefix target must be empty")
        state.hashes = list(hashes)
        max_pages = (
            len(hashes) if max_tokens is None
            else min(len(hashes), max_tokens // self.page_size)
        )
        n = 0
        for i in range(max_pages):
            page = self._pool.get(hashes[i])
            if page is None:
                break
            state.pages.append(page)
            self._ref[page] += 1
            self._lru.pop(page, None)
            n += 1
        tokens = n * self.page_size
        state.l_acc = tokens
        state.l_seq = tokens
        state.published = n
        return tokens

    def install_cached(self, h: str) -> int | None:
        """Install one externally-supplied page under hash `h` as a
        refcount-0 cached pool entry (the replication receive path).

        The page is immediately evictable — installing can displace only
        other cached pages, never referenced ones, so replication cannot
        OOM a healthy server. Returns the page id the caller must fill
        with the hash's content, or None when the hash is already pooled
        (nothing to do) or no free/cached page is reclaimable."""
        if h in self._pool:
            return None
        if self.max_cached_pages > 0 and self.max_cached_pages <= len(
            self._lru
        ):
            # keep the cap by evicting the coldest cached page first;
            # installing at the cap must not grow the pool
            cold, _ = self._lru.popitem(last=False)
            self._unpublish(cold)
            self._free.append(cold)
        if self._free:
            page = self._free.pop()
        elif self._lru:
            page, _ = self._lru.popitem(last=False)
            self._unpublish(page)
        else:
            return None
        self._pool[h] = page
        self._page_hash[page] = h
        self._ref[page] = 0
        self._lru[page] = None
        self._lru.move_to_end(page)
        return page

    def trim_adopted(self, seq_id: int, keep_tokens: int) -> None:
        """Shrink an adopted (still-unwritten) committed prefix to
        `keep_tokens` — the span chain agreed on a smaller common hit, or
        the client keeps the last prompt position uncached so the final
        step has an output. No-op when already at or below the target."""
        state = self._seqs[seq_id]
        if keep_tokens < 0:
            raise ValueError(f"keep_tokens must be >= 0, got {keep_tokens}")
        if keep_tokens >= state.l_acc or state.l_seq != state.l_acc:
            return
        state.l_acc = keep_tokens
        state.l_seq = keep_tokens
        self._trim(state)

    # ------------------------------------------------------- session parking
    def park_seq_cached(self, seq_id: int) -> tuple[list[str], int]:
        """Hand every page of `seq_id` to the pool as refcount-0 cached
        entries (session-lease park: wire/lease layer, not host d2h).

        Pages whose content is already pool-published keep their real hash;
        the rest get a synthetic "~parked:" identity so they too land in
        the cached LRU — immediately evictable under allocation pressure
        (a parked session can never OOM the server) yet resident for a
        cheap exact resume while memory lasts. Returns (per-page keys,
        committed length) — everything `unpark_seq_cached` needs."""
        state = self._seqs[seq_id]
        keys: list[str] = []
        l_acc = state.l_acc
        for i, page in enumerate(state.pages):
            h = self._page_hash.get(page)
            if h is None:
                h = f"~parked:{seq_id}:{i}:{page}"
                self._pool[h] = page
                self._page_hash[page] = h
            keys.append(h)
            self._release_page(page)
        state.pages = []
        state.l_acc = 0
        state.l_seq = 0
        state.published = 0
        state.hashes = None
        return keys, l_acc

    def unpark_seq_cached(
        self, seq_id: int, keys: list[str], l_acc: int
    ) -> bool:
        """Re-pin a cached-parked sequence: all-or-nothing. If any page was
        evicted (or the pool invalidated by an arena rebuild) the resume is
        impossible and the caller falls back to full replay. On success the
        sequence is exactly as it was at park time — same pages, same
        committed length, zero recompute."""
        state = self._seqs[seq_id]
        if state.pages or state.l_seq or state.l_acc:
            raise ValueError("unpark_seq_cached target must be empty")
        pages: list[int] = []
        for h in keys:
            page = self._pool.get(h)
            if page is None:
                return False  # evicted — nothing pinned yet, nothing leaks
            pages.append(page)
        for h, page in zip(keys, pages):
            self._ref[page] += 1
            self._lru.pop(page, None)
            if h.startswith("~parked:"):
                # the synthetic identity served its purpose; a private page
                # must not stay adoptable under a hash nobody can match
                self._unpublish(page)
        state.pages = pages
        state.l_acc = l_acc
        state.l_seq = l_acc
        return True

    def purge_parked(self, keys: list[str]) -> int:
        """Final reclaim of a reaped session's synthetic park entries:
        still-cached "~parked:" pages drop straight to the free list (their
        content is unreachable — no prefix chain ever hashes to them).
        Real-hash pages stay cached; they remain useful to the prefix
        cache. Returns pages freed."""
        freed = 0
        for h in keys:
            if not h.startswith("~parked:"):
                continue
            page = self._pool.get(h)
            if page is not None and self._ref[page] == 0:
                self._lru.pop(page, None)
                self._unpublish(page)
                self._free.append(page)
                freed += 1
        return freed

    def take_pending_copies(self) -> list[tuple[int, int]]:
        """Drain queued copy-on-write (src_page, dst_page) pairs; the
        caller applies the device copies before the write that triggered
        them scatters."""
        out = self._pending_copies
        self._pending_copies = []
        return out

    def invalidate_pool(self) -> None:
        """Forget every cached page (arena rebuilt — device bytes are
        garbage). Cached LRU pages drop to the free list; referenced pages
        just lose their pool identity."""
        for page in self._lru:
            self._free.append(page)
        self._lru.clear()
        self._pool.clear()
        self._page_hash.clear()
        for state in self._seqs.values():
            state.published = 0
            state.hashes = None

    def counts(self) -> dict:
        """Page accounting for the leak invariant:
        free + referenced + cached == num_pages."""
        referenced = sum(1 for r in self._ref if r > 0)
        return {
            "free": len(self._free),
            "referenced": referenced,
            "cached": len(self._lru),
        }

    # ---------------------------------------------------------- device plans
    def page_table(
        self, seq_ids: list[int], max_pages: int
    ) -> np.ndarray:
        """[B, max_pages] int32 page ids, padded with 0 (masked by length)."""
        out = np.zeros((len(seq_ids), max_pages), dtype=np.int32)
        for i, sid in enumerate(seq_ids):
            pages = self._seqs[sid].pages
            if len(pages) > max_pages:
                raise ValueError(
                    f"sequence {sid} has {len(pages)} pages > bucket {max_pages}"
                )
            out[i, : len(pages)] = pages
        return out

    def context_lens(
        self, seq_ids: list[int], committed_only: bool = False
    ) -> np.ndarray:
        """Per-sequence visible lengths; `committed_only` clamps to l_acc —
        the reference's gather_prefix clamp (paged_kv.py:265-316)."""
        return np.asarray(
            [
                self._seqs[s].l_acc if committed_only else self._seqs[s].l_seq
                for s in seq_ids
            ],
            dtype=np.int32,
        )

    def prefix_slots(self, seq_id: int, committed_only: bool = True) -> np.ndarray:
        """Flat slot ids of the sequence prefix, clamped to l_acc by default."""
        state = self._seqs[seq_id]
        n = state.l_acc if committed_only else state.l_seq
        positions = np.arange(n)
        pages = np.asarray(state.pages, dtype=np.int64)[
            positions // self.page_size
        ]
        return (pages * self.page_size + positions % self.page_size).astype(
            np.int32
        )
