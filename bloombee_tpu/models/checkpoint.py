"""HF checkpoint reading (safetensors, torch-free).

Replaces the reference's per-block HF-hub state-dict loading and .npy weight
conversion (/root/reference/src/bloombee/server/from_pretrained.py:58-548,
models/llama/block.py:329-384): server loads only its span's layers; client
loads only embeddings + final norm + lm head (reference
client/from_pretrained.py:17-70 skips `model.layers.*`).

Zero-egress note: model directories are local paths (config.json +
*.safetensors [+ index]); hub download plumbing can wrap this later.
"""

from __future__ import annotations

import json
import pathlib

import numpy as np
from safetensors import safe_open

from bloombee_tpu.models.spec import ModelSpec


class CheckpointReader:
    """Lazy tensor reader over a local HF model directory."""

    def __init__(self, model_dir: str | pathlib.Path):
        self.dir = pathlib.Path(model_dir)
        with open(self.dir / "config.json") as f:
            self.config = json.load(f)
        index_path = self.dir / "model.safetensors.index.json"
        if index_path.exists():
            with open(index_path) as f:
                index = json.load(f)
            self._weight_map = index["weight_map"]
        else:
            files = sorted(self.dir.glob("*.safetensors"))
            if not files:
                raise FileNotFoundError(f"no safetensors in {self.dir}")
            self._weight_map = {}
            for fp in files:
                with safe_open(fp, framework="numpy") as f:
                    for k in f.keys():
                        self._weight_map[k] = fp.name
        self._handles: dict[str, object] = {}

    def keys(self):
        return self._weight_map.keys()

    def has(self, name: str) -> bool:
        return name in self._weight_map

    def tensor(self, name: str) -> np.ndarray:
        fname = self._weight_map[name]
        h = self._handles.get(fname)
        if h is None:
            h = safe_open(self.dir / fname, framework="numpy")
            self._handles[fname] = h
        return h.get_tensor(name)

    def model_type(self) -> str:
        return self.config.get("model_type", "llama")


def read_tensor(reader: CheckpointReader, name: str, dtype=None):
    """Read one tensor as a jnp array with optional dtype cast (the shared
    helper for family weight converters)."""
    import jax.numpy as jnp

    w = jnp.asarray(reader.tensor(name))
    return w.astype(dtype) if dtype is not None else w


def stack_expert_weights(
    reader, expert_fmt: str, gate_name: str, up_name: str, down_name: str,
    n_experts: int, dtype=None,
) -> dict:
    """Stack per-expert gate/up/down matrices into [E, D, I] / [E, I, D]
    tensors (the dense-over-experts MoE layout shared by Mixtral and
    Qwen3-MoE loaders). expert_fmt receives the expert index."""
    import jax.numpy as jnp

    gates, ups, downs = [], [], []
    for e in range(n_experts):
        p = expert_fmt.format(e)
        gates.append(read_tensor(reader, f"{p}.{gate_name}.weight", dtype).T)
        ups.append(read_tensor(reader, f"{p}.{up_name}.weight", dtype).T)
        downs.append(read_tensor(reader, f"{p}.{down_name}.weight", dtype).T)
    return {
        "experts_gate": jnp.stack(gates),
        "experts_up": jnp.stack(ups),
        "experts_down": jnp.stack(downs),
    }


def load_spec(model_dir: str) -> ModelSpec:
    """ModelSpec from a local model dir via the family registry."""
    from bloombee_tpu.models.auto import spec_from_config_dict

    reader = CheckpointReader(model_dir)
    return spec_from_config_dict(reader.config)


def load_span_params(
    model_dir: str, start: int, end: int, dtype=None,
    adapter_dirs: list[str] | None = None,
):
    """Stacked per-layer params for blocks [start, end), with optional LoRA
    adapters merged into the base weights (W' = W + alpha/r * B A — the
    capability of the reference's utils/peft.py LoraLinear; merging at load
    keeps the serving path a plain matmul)."""
    from bloombee_tpu.models.auto import get_family
    from bloombee_tpu.utils.tree import stack_params

    reader = CheckpointReader(model_dir)
    family = get_family(reader.model_type())
    adapters = [LoraAdapter(d) for d in (adapter_dirs or [])]
    layers = []
    for i in range(start, end):
        params = family.load_block_params(reader, i, dtype=dtype)
        for adapter in adapters:
            params = adapter.merge_into(params, i)
        layers.append(params)
    spec = family.spec_from_config_dict(reader.config)
    if spec.heterogeneous:
        # per-layer shapes differ (gemma-4): no stacking — the hetero span
        # step unrolls over a tuple of per-layer param dicts
        return tuple(layers), spec
    return stack_params(layers), spec


def load_span_params_split(
    model_dir: str, start: int, end: int, resident: int, dtype=None,
    adapter_dirs: list[str] | None = None, weight_quant: str | None = None,
):
    """Weight-offload loader: returns (stacked_prefix, host_layers, spec).

    The first `resident` layers stack on device as usual; the remaining
    layers are pulled back to HOST memory (numpy pytrees) one at a time —
    the span's device footprint never exceeds the prefix plus one layer, so
    a server can serve a span larger than its HBM (reference FlexGen Policy
    weight percentages). `weight_quant` quantizes every layer (int8 halves
    / int4 quarters the host->device bytes streamed per step — the main
    lever on offloaded decode speed)."""
    import jax

    from bloombee_tpu.models import wquant
    from bloombee_tpu.models.auto import get_family
    from bloombee_tpu.utils.tree import stack_params

    reader = CheckpointReader(model_dir)
    family = get_family(reader.model_type())
    spec = family.spec_from_config_dict(reader.config)
    if spec.heterogeneous:
        raise ValueError("weight offload + heterogeneous spans unsupported")
    adapters = [LoraAdapter(d) for d in (adapter_dirs or [])]
    bits = {"int8": 8, "int4": 4}.get(weight_quant or "")
    prefix, host = [], []
    for i in range(start, end):
        params = family.load_block_params(reader, i, dtype=dtype)
        for adapter in adapters:
            params = adapter.merge_into(params, i)
        if bits:
            params = wquant.quantize_layer_params(params, bits)
        if i - start < resident:
            prefix.append(params)
        else:
            host.append(jax.device_get(params))
    stacked = stack_params(prefix) if prefix else None
    return stacked, host, spec


class LoraAdapter:
    """A PEFT-format LoRA adapter directory (adapter_config.json +
    adapter_model.safetensors)."""

    # our param name -> HF module suffix
    _TARGETS = {
        "q_proj": "self_attn.q_proj",
        "k_proj": "self_attn.k_proj",
        "v_proj": "self_attn.v_proj",
        "o_proj": "self_attn.o_proj",
        "gate_proj": "mlp.gate_proj",
        "up_proj": "mlp.up_proj",
        "down_proj": "mlp.down_proj",
    }

    def __init__(self, adapter_dir: str):
        d = pathlib.Path(adapter_dir)
        self.dir = d
        with open(d / "adapter_config.json") as f:
            cfg = json.load(f)
        import math

        r = cfg["r"]
        self.scaling = cfg["lora_alpha"] / (
            math.sqrt(r) if cfg.get("use_rslora") else r
        )
        files = sorted(d.glob("*.safetensors"))
        if not files:
            raise FileNotFoundError(f"no adapter safetensors in {d}")
        self._handles = [safe_open(f, framework="numpy") for f in files]
        self._key_to_handle = {
            k: h for h in self._handles for k in h.keys()
        }
        self.merged_tensors = 0

    def _find(self, layer_idx: int, target: str, which: str) -> str | None:
        suffix = f"layers.{layer_idx}.{target}.{which}.weight"
        for k in self._key_to_handle:
            if k.endswith(suffix):
                return k
        return None

    def _get(self, key: str) -> np.ndarray:
        return np.asarray(
            self._key_to_handle[key].get_tensor(key), dtype=np.float32
        )

    def span_factors(self, start: int, end: int, dtype=None) -> dict:
        """Stacked UNMERGED factors for blocks [start, end): per targeted
        projection, {"a": [L, in, r], "b": [L, r, out]} with the alpha/r
        scaling folded into b. This is the per-request adapter path
        (reference utils/peft.py `using_adapter` + LoraLinear): one base
        weight serves every adapter, the step adds (x a) b for the selected
        one. Layers the adapter doesn't target get zero factors."""
        import jax.numpy as jnp

        per_target: dict[str, dict] = {}
        for name, target in self._TARGETS.items():
            a_list: list = []
            b_list: list = []
            shapes = None
            for i in range(start, end):
                ka = self._find(i, target, "lora_A")
                kb = self._find(i, target, "lora_B")
                if ka is not None and kb is not None:
                    a = self._get(ka)  # PEFT A: [r, in]
                    b = self._get(kb)  # PEFT B: [out, r]
                    a_list.append(a.T)  # [in, r] for x @ a
                    b_list.append(b.T * self.scaling)  # [r, out]
                    shapes = (a.shape, b.shape)
                else:
                    a_list.append(None)
                    b_list.append(None)
            if shapes is None:
                continue
            (r, din), (dout, _) = shapes
            a_zero = np.zeros((din, r), np.float32)
            b_zero = np.zeros((r, dout), np.float32)
            a_stack = np.stack([a if a is not None else a_zero for a in a_list])
            b_stack = np.stack([b if b is not None else b_zero for b in b_list])
            per_target[name] = {
                "a": jnp.asarray(a_stack, dtype=dtype),
                "b": jnp.asarray(b_stack, dtype=dtype),
            }
        if not per_target:
            # distinguish "adapter targets other layers" (fine: this span
            # serves base weights, e.g. layers_to_transform adapters split
            # across servers) from "key layout mismatch" (a correctness
            # trap: NO server would ever apply the adapter)
            import re

            any_layer = any(
                re.search(
                    rf"layers\.\d+\.(?:{'|'.join(map(re.escape, self._TARGETS.values()))})\.lora_[AB]\.weight$",
                    k,
                )
                for k in self._key_to_handle
            )
            if not any_layer:
                raise ValueError(
                    f"adapter {self.dir} matched no tensors for ANY layer; "
                    f"adapter keys like "
                    f"{next(iter(self._key_to_handle), None)!r}"
                )
        return per_target

    def merge_into(self, params: dict, layer_idx: int) -> dict:
        import jax.numpy as jnp

        merged_here = 0
        for name, target in self._TARGETS.items():
            if name not in params:
                continue
            ka = self._find(layer_idx, target, "lora_A")
            kb = self._find(layer_idx, target, "lora_B")
            if ka is None or kb is None:
                continue
            a = self._get(ka)
            b = self._get(kb)
            delta = (b @ a).T * self.scaling  # [in, out], matches our layout
            params[name] = (
                params[name].astype(jnp.float32) + jnp.asarray(delta)
            ).astype(params[name].dtype)
            merged_here += 1
        if merged_here == 0:
            # silently serving base weights as "fine-tuned" would be a
            # correctness trap (fused-QKV families, or prefix-mismatched keys)
            raise ValueError(
                f"adapter {self.dir} matched no tensors for layer "
                f"{layer_idx}; param names {sorted(params)} vs adapter keys "
                f"like {next(iter(self._key_to_handle), None)!r}"
            )
        self.merged_tensors += merged_here
        return params


def load_adapter_factors(
    adapter_dir: str, start: int, end: int, dtype=None
) -> dict:
    """Unmerged stacked LoRA factors for a span (see
    LoraAdapter.span_factors) — the load half of per-request adapter
    switching."""
    return LoraAdapter(adapter_dir).span_factors(start, end, dtype=dtype)


def resolve_adapter(adapters: dict, name: str | None):
    """Shared adapter lookup: None -> base (no factors); unknown -> loud."""
    if name is None:
        return None
    try:
        return adapters[name]
    except KeyError:
        raise KeyError(
            f"unknown adapter {name!r}; serving "
            f"{sorted(adapters) or 'base only'}"
        ) from None


def load_client_params(model_dir: str, dtype=None) -> dict:
    """Embeddings + final norm + LM head (the client-side trio), plus any
    family extras (embedding layernorm, norm bias, tied heads)."""
    import jax.numpy as jnp

    from bloombee_tpu.models.auto import get_family

    reader = CheckpointReader(model_dir)
    family = get_family(reader.model_type())
    if family.client_loader is not None:
        return family.client_loader(reader, dtype=dtype)
    names = family.client_param_names()
    embed = jnp.asarray(reader.tensor(names["embed"]))
    norm = jnp.asarray(reader.tensor(names["norm"]))
    if reader.has(names["lm_head"]):
        head = jnp.asarray(reader.tensor(names["lm_head"])).T
    else:  # tied embeddings
        head = embed.T
    if dtype is not None:
        embed, norm, head = (
            embed.astype(dtype), norm.astype(dtype), head.astype(dtype)
        )
    return {"embed": embed, "norm": norm, "lm_head": head}
