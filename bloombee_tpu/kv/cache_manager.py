"""CacheManager: token-budget admission + session cache lifecycle + host tiering.

TPU-native replacement for the reference's MemoryCache + KVCacheManager pair
(/root/reference/src/bloombee/server/memory_cache.py:83-460,
memory_cache_manager.py:28-2160). The reference splits allocation across
handler processes and a runtime process via pipes and shared mp.Values; the
JAX runtime is process-hostile, so here everything is one asyncio process and
the cross-process machinery collapses into an asyncio.Condition.

Capabilities kept:
- token-budget admission with timeout (memory_cache.py `_schedule_alloc`)
- handle -> per-sequence cache state, freed on context exit
- speculative write / commit / rollback via the PagedKVTable
- HBM <-> host-DRAM tiering at page granularity (the FlexGen offload
  capability, flexgen_utils/pytorch_backend.py TorchMixedDevice) via
  `park_sequence` / `unpark_sequence`: a parked sequence's KV moves to host
  numpy and its device pages are freed for other sessions.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import functools
import itertools
import threading
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from bloombee_tpu.kv import arena as arena_ops
from bloombee_tpu.utils import clock, env, lockwatch

env.declare(
    "BBTPU_PARK_QUANT", bool, False,
    "quantize host-parked KV of dense arenas to int4 (4x less host DRAM)",
)
env.declare(
    "BBTPU_DISK_DIR", str, "",
    "directory for disk-parked KV memmaps (empty = system temp dir); the "
    "reference's TorchDisk tier",
)
env.declare(
    "BBTPU_KV_QUANT", str, "none",
    "KV cache quantization: none | int4 (group-wise 4-bit device arena + "
    "quantized host parking, ~3.2x token capacity; reference "
    "compression.py TorchCompressedDevice)",
)
env.declare(
    "BBTPU_PREFIX_CACHE", bool, False,
    "cross-session shared-prefix KV cache: finished sequences' committed "
    "prompt pages stay pooled under content hashes; new sessions whose "
    "prompt chain matches adopt them and prefill only the suffix "
    "(forces the pure-Python paged table)",
)
env.declare(
    "BBTPU_PREFIX_MAX_PAGES", int, 0,
    "cap on refcount-0 pages retained in the prefix pool "
    "(0 = bounded only by allocation pressure / LRU eviction)",
)


class AllocationTimeout(RuntimeError):
    pass


class ParkedKVLost(RuntimeError):
    """The background d2h copy of parked KV failed (e.g. disk full) after
    the device pages were already reused. The sequence's server-side KV is
    gone; the client recovers by replaying its token history onto a fresh
    allocation (the same path that handles a dead server)."""


class SessionKVLost(RuntimeError):
    """A session's KV no longer exists (the arena was rebuilt after a
    kernel failure consumed the donated buffers). Not a server fault: the
    server replies a typed `session_lost` so the client replays its token
    history onto a fresh chain WITHOUT banning the (healthy) peer
    (advisor, round 4)."""


@dataclasses.dataclass
class _Parked:
    """One parked sequence's KV: either still in flight to host (`future`
    resolves to the (k_host, v_host) tuple) or already resolved (`host`)."""

    l_acc: int
    l_seq: int
    host: tuple | None = None
    future: object | None = None  # concurrent.futures.Future

    def resolve(self) -> tuple:
        if self.host is None:
            try:
                self.host = self.future.result()
            except Exception as e:
                raise ParkedKVLost(
                    f"background park copy failed ({e!r}); KV for this "
                    "sequence is unrecoverable — replay the session"
                ) from e
            self.future = None
        return self.host


class _DaemonPool:
    """Two-worker submit() pool built on daemon threads.

    concurrent.futures.ThreadPoolExecutor joins its (non-daemon) workers at
    interpreter exit — with a PJRT-wedged d2h copy in flight that join
    blocks forever and the process can never exit. Daemon threads let the
    interpreter die with the wedge still pending."""

    def __init__(self, max_workers: int = 2, name: str = "kv-park"):
        import concurrent.futures
        import queue

        self._futures = concurrent.futures
        self._q: queue.Queue = queue.Queue()
        for i in range(max_workers):
            threading.Thread(
                target=self._worker, name=f"{name}-{i}", daemon=True
            ).start()

    def _worker(self):
        while True:
            fut, fn = self._q.get()
            if fut.set_running_or_notify_cancel():
                try:
                    fut.set_result(fn())
                except BaseException as e:  # noqa: BLE001 — relay to waiter
                    fut.set_exception(e)

    def submit(self, fn):
        fut = self._futures.Future()
        self._q.put((fut, fn))
        return fut


def _locked(fn):
    """Serialize table/arena mutations across the compute thread and the
    event loop (see CacheManager._lock)."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _reorder_all_layers(ak, av, src, dst):
    """Compact surviving speculative rows across all layers in one fused
    gather+scatter (module-level jit: compiles once per slot-count bucket).
    Slabs are pytrees (dense array or int4 QuantSlab) — every leaf shares
    the [L, S, ...] slot layout, so the move maps over leaves."""

    def move(a):
        return a.at[:, dst].set(a[:, src], mode="drop")

    return jax.tree.map(move, ak), jax.tree.map(move, av)


@dataclasses.dataclass
class CacheHandle:
    handle_id: int
    seq_ids: list[int]
    max_length: int

    @property
    def batch_size(self) -> int:
        return len(self.seq_ids)


class CacheManager:
    _global_seq_counter = itertools.count()
    _global_handle_counter = itertools.count()

    def __init__(
        self,
        num_layers: int,
        num_pages: int,
        page_size: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=None,
        quant: str | None = None,  # None -> BBTPU_KV_QUANT env default
        hetero_spec=None,  # ModelSpec with per-layer geometry (gemma-4)
        start_block: int = 0,
        oversubscribe: float = 1.0,  # admit up to this x capacity (parking)
        prefix_cache: bool | None = None,  # None -> BBTPU_PREFIX_CACHE env
    ):
        dtype = dtype or jnp.bfloat16
        if quant is None:
            quant = env.get("BBTPU_KV_QUANT")
        self.quant = None if quant in (None, "none") else quant
        if prefix_cache is None:
            prefix_cache = env.get("BBTPU_PREFIX_CACHE")
        self.prefix_cache = bool(prefix_cache)
        from bloombee_tpu.kv.paged_native import make_table

        self.table = make_table(
            num_pages, page_size, prefix_cache=self.prefix_cache
        )
        if self.prefix_cache:
            self.table.max_cached_pages = env.get("BBTPU_PREFIX_MAX_PAGES")
        # prefix-cache serving counters (rpc_info observability)
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        # KV replication receive counter (pages installed via kv_put)
        self.repl_pages_installed = 0
        # probe-adopted token counts per seq, consumed by trim_adopted once
        # the prefill's final skip arrives (also the idempotency guard: a
        # retried prefill must not trim real committed tokens)
        self._adopted: dict[int, int] = {}
        if hetero_spec is not None and hetero_spec.heterogeneous:
            from bloombee_tpu.runtime.hetero import make_hetero_arena

            self._make_arena = lambda: make_hetero_arena(
                hetero_spec, num_layers, start_block, num_pages, page_size,
                dtype, quant=self.quant,
            )
        else:
            self._make_arena = lambda: arena_ops.make_arena(
                num_layers, num_pages, page_size, n_kv_heads, head_dim,
                dtype, quant=self.quant,
            )
        self.arena = self._make_arena()
        # bumped by rebuild_arena(); sessions opened under an older epoch
        # hold table state describing KV that no longer exists
        self.arena_epoch = 0
        # per-seq validity epoch: stamped at allocation, RE-stamped on
        # rebuild for sequences whose KV was host-parked at that moment
        # (their copies survive the rebuild, so they stay servable)
        self._seq_epoch: dict[int, int] = {}
        self._live_seqs: set[int] = set()
        self.num_layers = num_layers
        self.page_size = page_size
        self.capacity_tokens = num_pages * page_size
        self._reserved_tokens = 0
        self._cond: asyncio.Condition | None = None
        # PROCESS-wide counters (class attributes set below), not
        # per-manager: a server that rebalances swaps in a fresh manager
        # while old sessions' handles are still live — per-manager counters
        # restarting at 0 would alias an old handle's seq ids onto a new
        # session's KV (epoch_valid would then wrongly pass)
        self._seq_counter = CacheManager._global_seq_counter
        self._handle_counter = CacheManager._global_handle_counter
        self._parked: dict[int, _Parked] = {}
        # session-lease parking (wire half-open / client-death domain):
        # seq_id -> (per-page pool keys, committed length, arena epoch at
        # park time). Distinct from _parked (host d2h tiering) — the pages
        # stay device-resident as refcount-0 cached pool entries
        self._lease_parked: dict[int, tuple[list[str], int, int]] = {}
        # handles whose token reservation was returned at lease-park time
        # (allocate()'s exit must not subtract it a second time)
        self._lease_released: set[int] = set()
        # d2h copies of parked KV run here so parking never stalls the
        # compute thread (the copy engine half of the reference's async
        # offload, mcm.py:972-1335); 2 workers keep host-link order sane
        self._park_pool = None  # created lazily on first park
        # over-subscription (the FlexGen serve-more-than-HBM-fits story):
        # admission may reserve up to oversubscribe x capacity; physical
        # page pressure is relieved by the reclaimer callback (the server
        # parks idle sessions' KV to host) invoked from write/unpark paths
        self.oversubscribe = max(float(oversubscribe), 1.0)
        self.reclaimer = None  # callable(need_pages, exclude_seq_ids) -> int
        # table mutations happen on BOTH the compute thread (steps,
        # reclaim-parking) and the event loop (session teardown): a
        # reentrant lock keeps them atomic (reentrant because the reclaimer
        # runs inside write_slots/ensure_resident which already hold it)
        self._lock = lockwatch.thread_lock("kv.cache_manager", reentrant=True)

    @property
    def admit_limit(self) -> int:
        """Max reservable tokens (the load-bearing over-subscription
        invariant, derived in exactly one place)."""
        return int(self.capacity_tokens * self.oversubscribe)

    # reference: ServerInfo.cache_tokens_left (handler.py:3256-3273 rpc_info)
    @property
    def tokens_left(self) -> int:
        """Admittable tokens (scaled by oversubscribe — that IS the
        admission limit, so routing must see it, not raw capacity)."""
        return self.admit_limit - self._reserved_tokens

    def _condition(self) -> asyncio.Condition:
        if self._cond is None:
            self._cond = asyncio.Condition()
        return self._cond

    # ------------------------------------------------------------- admission
    @contextlib.asynccontextmanager
    async def allocate(
        self, batch_size: int, max_length: int, timeout: float | None = None
    ):
        """Async context manager reserving `batch_size * max_length` tokens.

        Mirrors KVCacheManager.allocate_cache (memory_cache_manager.py:391-420):
        blocks until the budget fits or the timeout elapses; frees everything
        on exit.
        """
        # charge page-granular budget: a sequence of max_length tokens pins
        # ceil(max_length / page_size) whole pages
        per_seq = -(-max_length // self.page_size) * self.page_size
        need = batch_size * per_seq
        admit_limit = self.admit_limit
        if need > admit_limit:
            raise AllocationTimeout(
                f"request for {need} tokens exceeds capacity "
                f"{admit_limit}"
            )
        cond = self._condition()
        deadline = clock.deadline(timeout)
        async with cond:
            while self._reserved_tokens + need > admit_limit:
                remaining = None
                if deadline is not None:
                    remaining = deadline - clock.monotonic()
                    if remaining <= 0:
                        raise AllocationTimeout(
                            f"timed out waiting for {need} cache tokens"
                        )
                try:
                    await clock.cond_wait(cond, remaining)
                except asyncio.TimeoutError:
                    raise AllocationTimeout(
                        f"timed out waiting for {need} cache tokens"
                    ) from None
            self._reserved_tokens += need
        handle = CacheHandle(
            handle_id=next(self._handle_counter),
            seq_ids=[next(self._seq_counter) for _ in range(batch_size)],
            max_length=max_length,
        )
        with self._lock:
            for sid in handle.seq_ids:
                self.table.add_seq(sid)
                self._seq_epoch[sid] = self.arena_epoch
            self._live_seqs.update(handle.seq_ids)
        try:
            yield handle
        finally:
            with self._lock:
                for sid in handle.seq_ids:
                    if self.table.has_seq(sid):
                        self.table.drop_seq(sid)
                    self._parked.pop(sid, None)
                    self._seq_epoch.pop(sid, None)
                    self._adopted.pop(sid, None)
                    self._live_seqs.discard(sid)
                    entry = self._lease_parked.pop(sid, None)
                    if entry is not None and hasattr(
                        self.table, "purge_parked"
                    ):
                        self.table.purge_parked(entry[0])
            async with cond:
                if handle.handle_id in self._lease_released:
                    # the reservation already went back at lease-park time
                    self._lease_released.discard(handle.handle_id)
                else:
                    self._reserved_tokens -= need
                cond.notify_all()

    # ----------------------------------------------------------- device plans
    @_locked
    def write_slots(
        self, handle: CacheHandle, num_tokens: int, commit: bool = True
    ) -> np.ndarray:
        """[B * num_tokens] flat slots for this step's new tokens (row-major
        batch-then-token order, matching hidden.reshape(B*T, ...)).

        Atomic across the batch: page availability is pre-checked so a
        mid-batch OutOfPages cannot leave earlier sequences claiming tokens
        that were never written.
        """
        table = self.table
        need = 0
        for sid in handle.seq_ids:
            st = table.seq(sid)
            need += max(
                0,
                -(-(st.l_seq + num_tokens) // self.page_size)
                - st.num_pages,
            )
        if need > table.free_pages and self.reclaimer is not None:
            # over-subscribed: evict idle sessions' KV to host and retry
            self.reclaimer(need - table.free_pages, set(handle.seq_ids))
        if need > table.free_pages:
            from bloombee_tpu.kv.paged import OutOfPages

            raise OutOfPages(
                f"batch write needs {need} pages, only "
                f"{table.free_pages} free"
            )
        slots = np.concatenate(
            [
                table.assign_write_slots(sid, num_tokens, commit=commit)
                for sid in handle.seq_ids
            ]
        )
        # copy-on-write pairs queued by the assigns must land on device
        # BEFORE the step scatters into `slots` (dispatch order == device
        # order, same guarantee parking relies on)
        self._apply_pending_copies()
        return slots

    @_locked
    def write_slots_ragged(
        self, handle: CacheHandle, counts: list[int], commit: bool = False
    ) -> np.ndarray:
        """write_slots with a PER-SEQUENCE token count: [sum(counts)] flat
        slots, sequence-major in handle.seq_ids order (matching the ragged
        mixed-batch packing, where decode members contribute 1 token and
        the prefill-chunk member contributes its whole chunk).

        Same atomicity contract as write_slots: availability is pre-checked
        across all members so a mid-group OutOfPages cannot leave earlier
        members claiming tokens that were never written.
        """
        if len(counts) != len(handle.seq_ids):
            raise ValueError(
                f"{len(counts)} counts for {len(handle.seq_ids)} sequences"
            )
        table = self.table
        need = 0
        for sid, n in zip(handle.seq_ids, counts):
            st = table.seq(sid)
            need += max(
                0,
                -(-(st.l_seq + int(n)) // self.page_size) - st.num_pages,
            )
        if need > table.free_pages and self.reclaimer is not None:
            self.reclaimer(need - table.free_pages, set(handle.seq_ids))
        if need > table.free_pages:
            from bloombee_tpu.kv.paged import OutOfPages

            raise OutOfPages(
                f"ragged write needs {need} pages, only "
                f"{table.free_pages} free"
            )
        slots = np.concatenate(
            [
                table.assign_write_slots(sid, int(n), commit=commit)
                for sid, n in zip(handle.seq_ids, counts)
            ]
        )
        self._apply_pending_copies()
        return slots

    @_locked
    def truncate_speculative(
        self, handle: CacheHandle, lengths: list[int]
    ) -> None:
        """Partial rollback to a pre-dispatch l_seq snapshot: undoes one
        failed dispatch's speculative writes without discarding earlier
        still-speculative tokens (mid-stream prefill chunks)."""
        for sid, length in zip(handle.seq_ids, lengths):
            self.table.truncate_speculative(sid, int(length))

    def page_table(self, handle: CacheHandle, max_pages: int) -> np.ndarray:
        return self.table.page_table(handle.seq_ids, max_pages)

    def context_lens(
        self, handle: CacheHandle, committed_only: bool = False
    ) -> np.ndarray:
        return self.table.context_lens(handle.seq_ids, committed_only)

    @_locked
    def commit(self, handle: CacheHandle, lengths: list[int] | None = None):
        for i, sid in enumerate(handle.seq_ids):
            self.table.commit(sid, None if lengths is None else lengths[i])

    @_locked
    def rollback(self, handle: CacheHandle):
        for sid in handle.seq_ids:
            self.table.rollback(sid)

    def accept_speculative(
        self, handle: CacheHandle, accepted_indices: list
    ) -> None:
        """Compact surviving speculative KV rows onto the committed prefix
        and commit them (the on-device replacement for the reference's async
        reorder thread, memory_cache_manager.py:2011-2160).

        `accepted_indices[i]` lists row i's surviving tree-relative indices
        in path order (depth 0, 1, ...).
        """
        # an over-subscribed server may have parked this session between
        # rounds. Unpark OUTSIDE the lock — ensure_resident's d2h resolve
        # must not run with the manager lock held — then re-check under
        # it: the reclaimer (serving another session) may park us again
        # in the gap.
        while True:
            self.ensure_resident(handle)
            with self._lock:
                if any(sid in self._parked for sid in handle.seq_ids):
                    continue
                return self._accept_speculative(handle, accepted_indices)

    @_locked
    def _accept_speculative(
        self, handle: CacheHandle, accepted_indices: list
    ) -> None:
        src_all, dst_all = [], []
        for sid, idx in zip(handle.seq_ids, accepted_indices):
            st = self.table.seq(sid)
            idx = np.asarray(idx, dtype=np.int64)
            spec_slots = self.table.range_slots(sid, st.l_acc, st.l_seq)
            src_all.append(spec_slots[idx])
            dst_all.append(spec_slots[: len(idx)])
            self.table.accept(sid, len(idx))
        src = np.concatenate(src_all) if src_all else np.zeros(0, np.int32)
        dst = np.concatenate(dst_all) if dst_all else np.zeros(0, np.int32)
        keep = src != dst  # in-place rows need no move
        src, dst = src[keep], dst[keep]
        if len(src) == 0:
            return
        # pad to a small bucket so reorder compiles once per bucket
        from bloombee_tpu.runtime.executor import next_pow2

        n = next_pow2(len(src), floor=4)
        oob = self.capacity_tokens  # out-of-bounds slot => dropped scatter
        src_p = np.zeros((n,), np.int32)  # padded gathers read slot 0
        dst_p = np.full((n,), oob, np.int32)  # padded scatters are dropped
        src_p[: len(src)] = src
        dst_p[: len(dst)] = dst
        self.arena["k"], self.arena["v"] = _reorder_all_layers(
            self.arena["k"], self.arena["v"],
            jnp.asarray(src_p), jnp.asarray(dst_p),
        )

    def ensure_resident(self, handle: CacheHandle) -> None:
        """Unpark any parked sequences of this handle before a step (the
        demand-paging half of over-subscription), reclaiming pages from
        idle sessions when tight. Raises OutOfPages when nothing can be
        evicted — the client's retry path handles it.

        Deliberately NOT @_locked: unpark_sequence resolves the parked
        d2h future, and that resolve must run with the manager lock
        RELEASED (its whole point — see unpark_sequence). An @_locked
        wrapper here reentrantly defeats that and stalls every cache op
        on the server behind one session's host copy. Page accounting
        and the reclaimer still run under a short lock hold per
        sequence."""
        while True:
            with self._lock:
                parked = [
                    sid for sid in handle.seq_ids if sid in self._parked
                ]
                if not parked:
                    return
                sid = parked[0]
                l_seq = self._parked[sid].l_seq
                need = -(-l_seq // self.page_size)
                if (
                    need > self.table.free_pages
                    and self.reclaimer is not None
                ):
                    self.reclaimer(
                        need - self.table.free_pages, set(handle.seq_ids)
                    )
            try:
                self.unpark_sequence(sid)
            except KeyError:
                # raced with a lease-teardown purge between the scan and
                # the unpark; the entry is gone, re-scan what's left
                continue

    # ------------------------------------------------------- prefix cache
    def _apply_pending_copies(self) -> None:
        """Drain the table's queued copy-on-write page pairs into one fused
        device copy (the same gather+scatter jit the speculative accept
        uses). Caller holds the lock (write_slots / write paths)."""
        take = getattr(self.table, "take_pending_copies", None)
        if take is None:
            return
        pairs = take()
        if not pairs:
            return
        ps = self.page_size
        offs = np.arange(ps, dtype=np.int64)
        src = np.concatenate([s * ps + offs for s, _ in pairs])
        dst = np.concatenate([d * ps + offs for _, d in pairs])
        from bloombee_tpu.runtime.executor import next_pow2

        n = next_pow2(len(src), floor=4)
        oob = self.capacity_tokens  # out-of-bounds slot => dropped scatter
        src_p = np.zeros((n,), np.int32)  # padded gathers read slot 0
        dst_p = np.full((n,), oob, np.int32)  # padded scatters are dropped
        src_p[: len(src)] = src
        dst_p[: len(dst)] = dst
        self.arena["k"], self.arena["v"] = _reorder_all_layers(
            self.arena["k"], self.arena["v"],
            jnp.asarray(src_p), jnp.asarray(dst_p),
        )

    @_locked
    def adopt_prefix(self, handle: "CacheHandle", chains) -> list[int]:
        """Map each row's longest pooled prompt prefix into its (empty)
        sequence; returns per-row adopted token counts. Rows with no chain,
        non-empty state, or a parked copy adopt nothing. Adopted pages are
        refcount-pinned until the prefill's trim_adopted settles the final
        skip — or session teardown drops them."""
        out: list[int] = []
        for sid, chain in zip(handle.seq_ids, chains):
            matched = 0
            if (
                self.prefix_cache
                and chain
                and sid not in self._parked
                and hasattr(self.table, "adopt_prefix")
            ):
                st = self.table.seq(sid)
                if not (st.l_seq or st.l_acc or st.pages):
                    matched = self.table.adopt_prefix(
                        sid, chain, max_tokens=handle.max_length
                    )
                    if matched:
                        self._adopted[sid] = matched
                elif st.hashes is None:
                    # active seq (e.g. a retried probe): just attach the
                    # chain so its committed pages publish
                    self.table.set_seq_hashes(sid, chain)
            out.append(matched)
        return out

    @_locked
    def trim_adopted(self, handle: "CacheHandle", keep_tokens: int) -> None:
        """Settle a probe: shrink each adopted prefix to the chain-wide
        skip the client actually uses (min across spans, capped below the
        prompt length so the last position still computes) and record the
        hit. Idempotent — only sequences with an outstanding adoption are
        touched, so a retried prefill can't trim real tokens."""
        for sid in handle.seq_ids:
            adopted = self._adopted.pop(sid, None)
            if adopted is None:
                continue
            kept = min(keep_tokens, adopted)
            if kept < adopted:
                self.table.trim_adopted(sid, kept)
            if kept > 0:
                self.prefix_hits += 1
                self.prefix_hit_tokens += kept

    def has_adopted(self, handle: "CacheHandle") -> bool:
        """True while a probe's adoption awaits its prefill's settle."""
        return any(sid in self._adopted for sid in handle.seq_ids)

    @_locked
    def prefix_stats(self) -> dict:
        """Prefix-cache observability for rpc_info."""
        return {
            "prefix_hits": int(self.prefix_hits),
            "prefix_hit_tokens": int(self.prefix_hit_tokens),
            "cow_copies": int(getattr(self.table, "cow_count", 0)),
            "prefix_cached_pages": int(
                getattr(self.table, "cached_pages", 0)
            ),
            "repl_pages_installed": int(self.repl_pages_installed),
            # device-arena rebuilds after a failed donated dispatch: a
            # nonzero value means sessions lost KV to self-heal events,
            # which an operator should correlate with failover replays
            "arena_epoch": int(self.arena_epoch),
        }

    # ------------------------------------------------------- kv replication
    @property
    def repl_supported(self) -> bool:
        """Page payloads can be exported/installed byte-exact only on a
        dense unquantized arena (int4 slabs and hetero tuples have no
        single canonical page layout on the wire) with the prefix pool
        available to hold them."""
        return (
            self.prefix_cache
            and self.quant is None
            and not isinstance(self.arena["k"], tuple)
            and hasattr(self.table, "install_cached")
        )

    @_locked
    def export_pages(self, seq_id: int, lo_page: int, hi_page: int):
        """Gather sealed pages [lo_page, hi_page) of one sequence for
        replication. Returns (k_dev, v_dev, hi) — device arrays of shape
        [L, n * page_size, kv, hd] (the caller moves them to host off the
        compute thread) and the page bound actually exported, clamped to
        the fully-committed (sealed) prefix. None when the sequence has
        nothing exportable (parked, reset, or replication unsupported)."""
        if not self.repl_supported or not self.table.has_seq(seq_id):
            return None
        if seq_id in self._parked or seq_id in self._adopted:
            return None
        state = self.table.seq(seq_id)
        sealed = state.l_acc // self.page_size
        hi = min(hi_page, sealed, state.num_pages)
        if hi <= max(lo_page, 0):
            return None
        slots = self.table.range_slots(
            seq_id, lo_page * self.page_size, hi * self.page_size
        )
        idx = jnp.asarray(slots)
        return self.arena["k"][:, idx], self.arena["v"][:, idx], hi

    @_locked
    def install_replicated(self, hashes, k_pages, v_pages) -> int:
        """kv_put receive path: install replicated pages into the prefix
        pool as refcount-0 cached entries and scatter their bytes into the
        arena. `k_pages`/`v_pages` are host arrays [n, L, page_size, kv,
        hd] aligned with `hashes` (chain order — parents first). Pages the
        pool already holds, or that no free/cached page can back, are
        skipped; returns the number actually installed."""
        if not self.repl_supported:
            return 0
        want = (
            self.num_layers, self.page_size,
        ) + tuple(self.arena["k"].shape[2:])
        k_pages = np.asarray(k_pages)
        v_pages = np.asarray(v_pages)
        if (
            k_pages.shape != (len(hashes),) + want
            or v_pages.shape != k_pages.shape
        ):
            raise ValueError(
                f"replicated page payload {k_pages.shape} does not match "
                f"arena geometry {(len(hashes),) + want}"
            )
        pages, rows = [], []
        for i, h in enumerate(hashes):
            page = self.table.install_cached(h)
            if page is not None:
                pages.append(page)
                rows.append(i)
        if not pages:
            return 0
        ps = self.page_size
        offs = np.arange(ps, dtype=np.int64)
        slots = jnp.asarray(
            np.concatenate([p * ps + offs for p in pages]).astype(np.int32)
        )

        def flat(a):  # [m, L, ps, kv, hd] -> [L, m*ps, kv, hd]
            sel = a[np.asarray(rows)]
            return np.swapaxes(sel, 0, 1).reshape(
                a.shape[1], len(rows) * ps, *a.shape[3:]
            )

        self.arena["k"] = self.arena["k"].at[:, slots].set(
            jnp.asarray(flat(k_pages)).astype(self.arena["k"].dtype)
        )
        self.arena["v"] = self.arena["v"].at[:, slots].set(
            jnp.asarray(flat(v_pages)).astype(self.arena["v"].dtype)
        )
        self.repl_pages_installed += len(pages)
        return len(pages)

    @_locked
    def extend_seq_hashes(self, handle: "CacheHandle", chains) -> None:
        """Attach each row's full-history hash chain (replication keeps
        them growing past the prompt) so the primary's own sealed decode
        pages publish locally too. Extend-only: a shorter chain than the
        one on record is ignored (a stale replication message)."""
        if not self.prefix_cache or not hasattr(
            self.table, "set_seq_hashes"
        ):
            return
        for sid, chain in zip(handle.seq_ids, chains):
            if not chain or not self.table.has_seq(sid):
                continue
            if sid in self._parked or sid in self._adopted:
                continue
            st = self.table.seq(sid)
            if st.hashes is not None and len(chain) < len(st.hashes):
                continue
            self.table.set_seq_hashes(sid, chain)

    # ------------------------------------------------------ session leases
    def _handle_need(self, handle: "CacheHandle") -> int:
        """The token reservation allocate() charged for this handle
        (page-granular, same formula)."""
        per_seq = -(-handle.max_length // self.page_size) * self.page_size
        return handle.batch_size * per_seq

    async def lease_park(self, handle: "CacheHandle") -> None:
        """Park a stream-dead session for the lease window.

        Speculative tokens roll back, then every sequence's pages are
        handed to the prefix pool as refcount-0 *cached* entries (the
        install_cached trick the replication standbys use): immediately
        evictable under allocation pressure — a parked session can never
        OOM the server — yet device-resident for an exact zero-recompute
        resume while memory lasts. Tables without a prefix pool fall back
        to host-tier parking (same resume contract, a d2h/h2d copy more).
        The session's token reservation is returned to the admission
        budget for the duration of the park."""
        with self._lock:
            for sid in handle.seq_ids:
                if sid in self._parked or not self.table.has_seq(sid):
                    continue  # already host-parked: the copy survives as-is
                if sid in self._lease_parked:
                    continue
                self.table.rollback(sid)
                # an unsettled probe adoption parks as plain committed
                # pages (their hashes are real — resume re-pins them)
                self._adopted.pop(sid, None)
                if hasattr(self.table, "park_seq_cached"):
                    keys, l_acc = self.table.park_seq_cached(sid)
                    self._lease_parked[sid] = (keys, l_acc, self.arena_epoch)
                elif self.table.seq(sid).l_seq > 0:
                    self.park_sequence(sid)
            self._lease_released.add(handle.handle_id)
        cond = self._condition()
        async with cond:
            self._reserved_tokens -= self._handle_need(handle)
            cond.notify_all()

    async def lease_resume(self, handle: "CacheHandle") -> bool:
        """Re-pin a lease-parked session on reconnect. All-or-nothing:
        True means every sequence is back exactly as parked (same pages,
        same committed lengths — zero recompute); False means at least one
        page was evicted (or the arena rebuilt) and the caller must treat
        the session as lost (full-replay fallback, then reclaim)."""
        cond = self._condition()
        async with cond:
            if handle.handle_id in self._lease_released:
                # re-acquire the reservation. This may transiently push
                # reserved past admit_limit — acceptable: the pages backing
                # the resume were evictable all along, so this cannot OOM,
                # and admission pressure re-equalizes as sessions close
                self._reserved_tokens += self._handle_need(handle)
                self._lease_released.discard(handle.handle_id)
        with self._lock:
            if not self.epoch_valid(handle):
                return False
            for sid in handle.seq_ids:
                entry = self._lease_parked.get(sid)
                if entry is None:
                    continue  # host-parked fallback: next step unparks it
                keys, l_acc, epoch = entry
                if epoch != self.arena_epoch:
                    return False
                if not self.table.unpark_seq_cached(sid, keys, l_acc):
                    return False
                del self._lease_parked[sid]
            return True

    @_locked
    def lease_reclaim(self, handle: "CacheHandle") -> None:
        """Final reclaim of a reaped (or unresumable) session: purge its
        synthetic park entries so those pages return to the free list now
        instead of lingering as unreachable cached entries. Real-hash
        pages stay pooled — they still serve the prefix cache. The rest of
        the teardown (drop_seq, reservation) happens at allocate() exit."""
        for sid in handle.seq_ids:
            entry = self._lease_parked.pop(sid, None)
            if entry is not None and hasattr(self.table, "purge_parked"):
                self.table.purge_parked(entry[0])

    def has_lease_parked(self, handle: "CacheHandle") -> bool:
        return any(sid in self._lease_parked for sid in handle.seq_ids)

    # ------------------------------------------------------- host tiering
    @_locked
    def park_sequence(self, seq_id: int, tier: str = "host") -> None:
        """Move one sequence's KV off the device and free its pages.

        tier="host": KV lands in host DRAM numpy (optionally int4 via
        BBTPU_PARK_QUANT). tier="disk": KV lands in a memmapped file under
        BBTPU_DISK_DIR — the third tier of the reference's FlexGen substrate
        (pytorch_backend.py TorchDisk, np.memmap-backed tensors).
        Lengths are preserved; `unpark_sequence` restores (possibly to
        different pages).

        ASYNC: only the device-side gather (and optional int4 quantize) runs
        here; pages are freed immediately and the d2h copy overlaps ongoing
        serving on a background thread (the copy-engine overlap of the
        reference's async offload, memory_cache_manager.py:972-1335).
        Freeing before the copy lands is safe: the gather is dispatched
        before any later step that could write the freed slots, and the
        device executes dispatches in order. Until the copy drains, the
        gathered slice transiently holds its bytes in HBM (int4 planes when
        quantized parking is on).
        """
        if tier not in ("host", "disk"):
            # before the expensive d2h copy, not after
            raise ValueError(f"unknown park tier {tier!r}")
        if seq_id in self._adopted:
            # probe-adopted, prefill imminent: parking now would record the
            # un-trimmed adopted length and desync the client's suffix
            # offset on unpark — skip; the reclaimer finds other victims
            return
        slots = self.table.prefix_slots(seq_id, committed_only=False)
        state = self.table.seq(seq_id)

        hetero = isinstance(self.arena["k"], tuple)
        if self.quant is None and not hetero and env.get("BBTPU_PARK_QUANT"):
            # dense arena, quantized parking: quantize the still-device-
            # resident slice FIRST so only the int4 planes cross the link —
            # 4x less host DRAM and d2h transfer (the host-side half of the
            # reference's compressed offload)
            from bloombee_tpu.kv import quant as q

            k_dev = q.quantize(self.arena["k"][:, slots])
            v_dev = q.quantize(self.arena["v"][:, slots])
        else:

            def take(a):
                return a[:, slots]

            k_dev = jax.tree.map(take, self.arena["k"])  # [L, n, kv, hd]
            v_dev = jax.tree.map(take, self.arena["v"])

        def fetch(k_dev=k_dev, v_dev=v_dev, tier=tier, seq_id=seq_id):
            k_host = jax.tree.map(np.asarray, k_dev)
            v_host = jax.tree.map(np.asarray, v_dev)
            if tier == "disk":
                k_host = jax.tree.map(
                    lambda a, tag=("k", seq_id): self._to_disk(a, *tag),
                    k_host,
                )
                v_host = jax.tree.map(
                    lambda a, tag=("v", seq_id): self._to_disk(a, *tag),
                    v_host,
                )
            return k_host, v_host

        if self._park_pool is None:
            self._park_pool = _DaemonPool()
        self._parked[seq_id] = _Parked(
            l_acc=state.l_acc,
            l_seq=state.l_seq,
            future=self._park_pool.submit(fetch),
        )
        # free device pages but keep the seq registered with zero length
        self.table.reset_seq(seq_id)

    _disk_counter = itertools.count()

    def _to_disk(self, arr: np.ndarray, kind: str, seq_id: int) -> np.ndarray:
        """Spill one parked leaf to a memmapped file (deleted when the
        memmap is garbage-collected via the unlink-after-open trick on
        POSIX: the file keeps living until the mapping drops)."""
        import os
        import tempfile

        if arr.size == 0:
            return arr  # np.memmap cannot map an empty file
        disk_dir = env.get("BBTPU_DISK_DIR") or tempfile.gettempdir()
        os.makedirs(disk_dir, exist_ok=True)
        path = os.path.join(
            disk_dir,
            f"bbtpu_kv_{os.getpid()}_{kind}{seq_id}_"
            f"{next(self._disk_counter)}.bin",
        )
        mm = np.memmap(path, dtype=arr.dtype, mode="w+", shape=arr.shape)
        mm[:] = arr
        mm.flush()
        os.unlink(path)  # POSIX: mapping keeps the data until released
        return mm

    def unpark_sequence(self, seq_id: int) -> None:
        with self._lock:
            entry = self._parked[seq_id]
        # resolve OUTSIDE the manager lock: the d2h copy is usually long
        # done (the sequence sat parked precisely because it was idle),
        # but when it isn't, blocking here must not stall every other
        # cache operation behind this one future
        try:
            k_host, v_host = entry.resolve()
        except ParkedKVLost:
            # the copy is gone for good: drop the entry so the client's
            # replay lands on a clean zero-length sequence, not a wedge
            with self._lock:
                if self._parked.get(seq_id) is entry:
                    del self._parked[seq_id]
            raise
        self._unpark_restore(seq_id, entry, k_host, v_host)

    @_locked
    def _unpark_restore(self, seq_id, entry, k_host, v_host) -> None:
        """Second half of unpark: re-check ownership under the lock (a
        concurrent lease teardown may have purged the entry while the
        future resolved), then scatter the host copy back into the arena."""
        if self._parked.get(seq_id) is not entry:
            raise KeyError(seq_id)
        l_acc, l_seq = entry.l_acc, entry.l_seq
        state = self.table.seq(seq_id)
        assert state.l_seq == 0, "unpark target must be empty"
        # may raise OutOfPages: the parked host copy must survive a failed
        # attempt, so only drop it once slots are secured; recovery owner:
        # on failure the seq simply stays empty+parked (nothing committed
        # yet), so there is nothing to roll back
        slots_np = self.table.assign_write_slots(
            seq_id, l_seq, commit=False)  # bbtpu: noqa[BB001]
        del self._parked[seq_id]
        self.table.restore_committed(seq_id, l_acc)
        slots = jnp.asarray(slots_np)
        from bloombee_tpu.kv.quant import QuantSlab, dequantize

        if self.quant is None and isinstance(k_host, QuantSlab):
            k_host = dequantize(
                QuantSlab(*(jnp.asarray(x) for x in k_host)),
                self.arena["k"].dtype,
            )
            v_host = dequantize(
                QuantSlab(*(jnp.asarray(x) for x in v_host)),
                self.arena["v"].dtype,
            )

        def put(a, h):
            return a.at[:, slots].set(jnp.asarray(h))

        self.arena["k"] = jax.tree.map(put, self.arena["k"], k_host)
        self.arena["v"] = jax.tree.map(put, self.arena["v"], v_host)

    def parked_seqs(self) -> Iterator[int]:
        return iter(self._parked)

    # ------------------------------------------------------------- recovery
    @_locked
    def rebuild_arena(self) -> None:
        """Replace a consumed arena with a fresh zeroed one after a kernel
        failure destroyed the donated buffers mid-chain (e.g. a paged
        failure between layer_step calls on the offload path). Every live
        device-RESIDENT sequence's KV is gone: their table state resets to
        zero length and their validity epoch goes stale, so the server
        fails their next step with a typed `session_lost` and the client
        replays history onto a fresh chain (the same path that handles a
        dead server). Host-parked sequences keep their copies AND get
        re-stamped to the new epoch: their next step unparks into the
        fresh arena intact, no replay needed (advisor, round 4)."""
        for sid in list(self._live_seqs):
            if self.table.has_seq(sid) and sid not in self._parked:
                self.table.reset_seq(sid)
        # pooled pages describe the OLD arena's bytes — a hit against them
        # would serve garbage KV
        if hasattr(self.table, "invalidate_pool"):
            self.table.invalidate_pool()
        self._adopted.clear()
        self.arena = self._make_arena()
        self.arena_epoch += 1
        for sid in self._parked:
            if sid in self._seq_epoch:
                self._seq_epoch[sid] = self.arena_epoch

    @_locked
    def is_fresh(self, handle: "CacheHandle") -> bool:
        """True iff every sequence in `handle` has NO server-side state at
        all: zero committed/speculative length AND nothing parked to host.
        (A parked sequence's table length reads 0 — its KV lives in
        `_parked` — so a bare length check would misclassify it as fresh;
        the sp-prefill eligibility gate needs the distinction.)"""
        for sid in handle.seq_ids:
            if sid in self._parked:
                return False
            state = self.table.seq(sid)
            if state.l_seq or state.l_acc:
                return False
        return True

    @_locked
    def memory_stats(self) -> dict:
        """KV-side byte/token accounting for the memory-observability
        surface (utils/memory.py) — kept here so it reads this manager's
        state through one accessor instead of private attributes."""
        from bloombee_tpu.utils.memory import tree_nbytes

        parked_resolved = 0
        parked_total = 0
        for entry in self._parked.values():
            parked_total += 1
            if entry.host is not None:
                parked_resolved += tree_nbytes(entry.host)
        return {
            "kv_arena_bytes": tree_nbytes(self.arena),
            "parked_kv_host_bytes": parked_resolved,
            "parked_seqs": parked_total,
            "kv_tokens_reserved": int(self._reserved_tokens),
            "kv_tokens_capacity": int(self.capacity_tokens),
        }

    @_locked
    def combine_handles(self, handles: list["CacheHandle"]) -> "CacheHandle":
        """Merged view over several live handles for ONE batched decode
        step (continuous batching). The combined seq_id list is what drives
        cross-session page-table row gathering: `page_table` /
        `write_slots` / `context_lens` already operate per-sequence over
        `handle.seq_ids`, so rows from different sessions compose into one
        kernel launch with no new table machinery.

        The combined handle is EPHEMERAL — it borrows the member sessions'
        sequences for the duration of one dispatch and is never registered
        (handle_id=-1), so dropping it frees nothing and it must not
        outlive the member allocations."""
        return CacheHandle(
            handle_id=-1,
            seq_ids=[sid for h in handles for sid in h.seq_ids],
            max_length=max(h.max_length for h in handles),
        )

    @_locked
    def has_parked(self, handle: "CacheHandle") -> bool:
        """True when any sequence of `handle` is host-parked, i.e. its next
        step must unpark first. The decode batcher runs such members solo:
        an unpark inside a merged dispatch could raise OutOfPages for the
        whole group, failing sessions whose KV was resident all along."""
        return any(sid in self._parked for sid in handle.seq_ids)

    @_locked
    def epoch_valid(self, handle: "CacheHandle") -> bool:
        """True iff every sequence in `handle` still has servable KV: its
        validity epoch matches the current arena epoch (either no rebuild
        happened since allocation, or the seq was host-parked through every
        rebuild)."""
        return all(
            self._seq_epoch.get(sid) == self.arena_epoch
            for sid in handle.seq_ids
        )
