"""Deterministic fault injection for the wire layer.

BloomBee's value proposition is surviving a flaky swarm, but the reactive
machinery (session re-route + replay, registry TTL expiry, peer bans) could
only be exercised by killing real servers at uncontrolled moments. This
module makes failures *provokable*: a `FaultPlan` holds an ordered list of
`FaultRule`s plus a seeded RNG, and `Connection._send` / `Connection._read_loop`
consult the installed plan on every frame. Rules match per-site, per-method,
per-peer-port and per-nth-call, so a test can say exactly "reset the
connection to server B on the 3rd decode step" and replay it bit-for-bit.

Actions:

- ``delay``  — sleep ``delay_s`` before the frame proceeds (slow link)
- ``reset``  — abort the transport (RST-style connection reset)
- ``close``  — orderly close mid-stream (FIN after the current frame)
- ``stall``  — on read: swallow the frame and never deliver it (wedged peer);
  on send: sleep until the connection dies (stalled writer)
- ``drop``   — on read: silently discard the frame (lost packet)
- ``corrupt`` — on send: perturb the first float tensor payload in-flight
  (seeded pick of NaN-poison, large scale, or an exponent bit-flip), then
  re-serialize so the frame stays *well-formed* — header, sizes and codec
  all valid, only the numbers are wrong. Unlike every omission action
  above, nothing at the transport layer ever notices; only the integrity
  layer (client sanity gate / out_digest / audits) can.
- ``partition`` — once triggered, blackhole the connection in BOTH
  directions forever: every later send is silently discarded before the
  wire and every later read is swallowed, with no FIN/RST ever delivered.
  Unlike per-frame ``stall``/``drop`` the connection *stays* dead — the
  half-open TCP case only keepalives (wire/rpc.py) can detect.

Probabilistic chaos uses the plan's seeded RNG so a failing soak run can be
reproduced from its seed alone. Env knobs (``BBTPU_CHAOS_*``) build a
process-wide plan at first use for chaos-testing real deployments without
touching code.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
from typing import Callable, Optional

import ml_dtypes
import numpy as np

from bloombee_tpu.utils import env, ledger

logger = logging.getLogger(__name__)

env.declare(
    "BBTPU_CHAOS", bool, False,
    "master switch: build a process-wide FaultPlan from the BBTPU_CHAOS_* "
    "knobs below and inject faults into every wire connection",
)
env.declare(
    "BBTPU_CHAOS_SEED", int, 0,
    "seed for the chaos plan's RNG — identical seeds replay identical "
    "fault sequences",
)
env.declare(
    "BBTPU_CHAOS_DELAY_P", float, 0.0,
    "per-frame probability of delaying a sent frame",
)
env.declare(
    "BBTPU_CHAOS_DELAY_S", float, 0.05,
    "how long a chaos-delayed frame sleeps before hitting the wire",
)
env.declare(
    "BBTPU_CHAOS_RESET_P", float, 0.0,
    "per-frame probability of aborting the connection instead of sending",
)
env.declare(
    "BBTPU_CHAOS_STALL_P", float, 0.0,
    "per-frame probability of swallowing a received frame (wedged peer)",
)
env.declare(
    "BBTPU_CHAOS_PARTITION_P", float, 0.0,
    "per-frame probability of partitioning the connection: a permanent "
    "both-direction blackhole with no FIN/RST (detected only by keepalives)",
)
env.declare(
    "BBTPU_CHAOS_CORRUPT_P", float, 0.0,
    "per-frame probability of corrupting a span-output reply tensor "
    "in-flight (well-formed frame, wrong numbers); only the integrity "
    "layer can detect it, so pair with BBTPU_INTEGRITY=1",
)
env.declare(
    "BBTPU_CHAOS_SCHEDULE", str, "",
    "scripted deterministic faults: ';'-separated STEP:ACTION[:PORT] "
    "entries, e.g. '3:reset;7:partition:7711' — at the Nth span-output "
    "decode-step reply (per entry, counted over frames matching the "
    "entry's PORT filter), fire the wire ACTION exactly once. Works with "
    "BBTPU_CHAOS=0 (a schedule alone arms the plan). The 'crash' action "
    "is in-process only (needs a bound callback) and is rejected here",
)


class InjectedFault(ConnectionResetError):
    """Raised on the faulting side so callers see the same exception family
    a real transport failure produces (retry paths must not special-case
    injected faults — that would test nothing)."""


@dataclasses.dataclass
class FaultRule:
    """One programmable fault. A rule matches a frame when every non-None
    constraint holds; it fires on the ``nth`` match (1-based) and the
    following ``count - 1`` matches (count=0 -> every match from nth on)."""

    site: str  # "send" | "read"
    # "delay" | "reset" | "close" | "stall" | "drop" | "partition" | "corrupt"
    action: str
    method: str | None = None  # frame's "m" (rpc method) or "t" (frame type)
    port: int | None = None  # remote peer port (targets one server)
    nth: int = 1
    count: int = 1
    delay_s: float = 0.0
    prob: float | None = None  # None: deterministic; else seeded coin-flip
    predicate: Optional[Callable[[dict], bool]] = None  # extra meta match
    _matches: int = dataclasses.field(default=0, repr=False)
    _fired: int = dataclasses.field(default=0, repr=False)

    def wants(self, site: str, peer: tuple | None, header: dict,
              rng: random.Random) -> bool:
        if site != self.site:
            return False
        if self.method is not None and self.method not in (
            header.get("m"), header.get("t")
        ):
            return False
        if self.port is not None and (peer is None or peer[1] != self.port):
            return False
        if self.predicate is not None and not self.predicate(header):
            return False
        if self.prob is not None:
            return rng.random() < self.prob
        self._matches += 1
        if self._matches < self.nth:
            return False
        if self.count and self._fired >= self.count:
            return False
        self._fired += 1
        return True


@dataclasses.dataclass
class ScheduledFault:
    """One scripted fault: "at decode step N, do X". Unlike a FaultRule
    (which matches frame shapes, possibly probabilistically), a scheduled
    fault counts *span-output decode-step replies* — the swarm's logical
    clock — so a test can script "crash server B at step 3" and assert the
    exact recovery sequence that follows, bit-for-bit, run after run.

    ``action`` is any wire action ("delay"/"reset"/"close"/"stall"/"drop"/
    "partition"/"corrupt") or ``"crash"`` — a hard process-death of the
    server named by ``target``, delivered via a callback the test harness
    binds with FaultSchedule.bind_crash (env schedules cannot express it).
    ``port`` filters which peer's replies advance this entry's counter."""

    at_step: int  # 1-based index among this entry's matching replies
    action: str
    port: int | None = None  # count only replies to/from this peer port
    target: str | None = None  # crash only: bind_crash() callback name
    delay_s: float = 0.05  # delay action only
    fired: bool = dataclasses.field(default=False, repr=False)
    _seen: int = dataclasses.field(default=0, repr=False)


class FaultSchedule:
    """Ordered scripted faults, consulted by the plan on every span-output
    reply frame BEFORE the probabilistic rules. Each entry keeps its own
    step counter, so two entries with different port filters tick
    independently. Fired entries never re-fire."""

    def __init__(self, faults: list[ScheduledFault] | None = None,
                 site: str = "send"):
        # steps are counted at ONE site only: in-process swarms share a
        # single plan between client and server connections, and counting
        # a reply at both its send AND its read would tick every entry
        # twice per step. "send" (the server emitting the reply) is the
        # default; a client-process-only deployment can count at "read".
        self.site = site
        self.faults = list(faults or [])
        self._crash_cbs: dict[str, Callable[[], None]] = {}
        # observability: tests assert exactly which steps faulted
        self.log: list[tuple[int, str, str | int | None]] = []

    def add(self, fault: ScheduledFault) -> "FaultSchedule":
        self.faults.append(fault)
        return self

    def bind_crash(self, name: str, cb: Callable[[], None]) -> "FaultSchedule":
        """Bind a crash target: ``cb`` (typically BlockServer.crash) runs
        when an entry with action='crash', target=name comes due."""
        self._crash_cbs[name] = cb
        return self

    def pending(self) -> list[ScheduledFault]:
        return [f for f in self.faults if not f.fired]

    def due(self, peer: tuple | None) -> list[ScheduledFault]:
        """Advance every live entry's counter by this one matching reply
        frame; return the entries that just came due (usually 0 or 1)."""
        out = []
        for f in self.faults:
            if f.fired:
                continue
            if f.port is not None and (peer is None or peer[1] != f.port):
                continue
            f._seen += 1
            if f._seen >= f.at_step:
                f.fired = True
                out.append(f)
        return out

    @classmethod
    def from_env(cls) -> "FaultSchedule | None":
        """Parse BBTPU_CHAOS_SCHEDULE ('STEP:ACTION[:PORT];...'); None when
        unset. Rejects 'crash' loudly — a process death needs an in-process
        bound callback, which no env string can carry."""
        spec = str(env.get("BBTPU_CHAOS_SCHEDULE")).strip()
        if not spec:
            return None
        faults = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            parts = [p.strip() for p in entry.split(":")]
            if len(parts) < 2:
                raise ValueError(
                    f"BBTPU_CHAOS_SCHEDULE entry {entry!r}: want "
                    "STEP:ACTION[:PORT]"
                )
            action = parts[1]
            if action == "crash":
                raise ValueError(
                    "BBTPU_CHAOS_SCHEDULE cannot script 'crash': it needs "
                    "an in-process FaultSchedule.bind_crash() callback"
                )
            faults.append(ScheduledFault(
                at_step=int(parts[0]), action=action,
                port=int(parts[2]) if len(parts) > 2 else None,
            ))
        return cls(faults)


class FaultPlan:
    """Seeded, ordered rule set consulted by every Connection."""

    def __init__(self, rules: list[FaultRule] | None = None,
                 seed: int = 0, schedule: FaultSchedule | None = None):
        self.rules = list(rules or [])
        self.rng = random.Random(seed)
        self.schedule = schedule
        # observability: tests assert exactly which faults landed
        self.log: list[tuple[str, str, dict]] = []

    def add(self, rule: FaultRule) -> "FaultPlan":
        self.rules.append(rule)
        return self

    def _pick(self, site: str, peer, header) -> FaultRule | None:
        for rule in self.rules:
            if rule.wants(site, peer, header, self.rng):
                return rule
        return None

    async def on_send(self, conn, header: dict,
                      blobs: list[bytes] | None = None) -> str | None:
        """Consulted by Connection._send before the frame hits the wire.
        May sleep, raise InjectedFault after aborting the transport,
        mutate ``header``/``blobs`` in place (corrupt — the caller encodes
        the frame afterwards, so sizes are recomputed), or return "drop"
        to silently discard the frame (partition)."""
        if getattr(conn, "_bbtpu_partitioned", False):
            return "drop"
        if (
            self.schedule is not None
            and self.schedule.site == "send"
            and _is_span_output_reply(header)
        ):
            verdict = await self._fire_scheduled("send", conn, header, blobs)
            if verdict is not None:
                return verdict
        rule = self._pick("send", conn.peer, header)
        if rule is None:
            return None
        self.log.append(("send", rule.action, dict(header)))
        ledger.fault(f"wire.{rule.action}")
        if rule.action == "partition":
            self._partition(conn)
            return "drop"
        if rule.action == "corrupt":
            self._corrupt(header, blobs)
            return None
        await self._apply(conn, rule, header)
        return None

    async def on_read(self, conn, header: dict) -> str | None:
        """Consulted by Connection._read_loop after decoding a frame and
        before dispatch. Returns "drop" to swallow the frame."""
        if getattr(conn, "_bbtpu_partitioned", False):
            return "drop"
        if (
            self.schedule is not None
            and self.schedule.site == "read"
            and _is_span_output_reply(header)
        ):
            verdict = await self._fire_scheduled("read", conn, header, None)
            if verdict is not None:
                return verdict
        rule = self._pick("read", conn.peer, header)
        if rule is None:
            return None
        self.log.append(("read", rule.action, dict(header)))
        ledger.fault(f"wire.{rule.action}")
        if rule.action == "partition":
            self._partition(conn)
            return "drop"
        if rule.action == "delay":
            await asyncio.sleep(rule.delay_s)
            return None
        if rule.action in ("stall", "drop"):
            logger.info(
                "chaos: swallowing %s frame from %s", header.get("t"),
                conn.peer,
            )
            return "drop"
        if rule.action in ("reset", "close"):
            await self._kill(conn, abort=rule.action == "reset")
            return "drop"
        return None

    async def _fire_scheduled(self, site: str, conn, header: dict,
                              blobs: list | None) -> str | None:
        """Apply every scheduled fault due at this span-output reply.
        Returns "drop" to discard the frame, None to let it proceed.
        Scheduled "stall"/"drop" both swallow the reply (the deterministic
        harness must never hang a writer on a wall-clock wait); "crash"
        runs the bound callback and drops the in-flight reply — it dies
        with the server, exactly like a mid-step kill -9."""
        verdict = None
        for f in self.schedule.due(conn.peer):
            self.schedule.log.append((f._seen, f.action, f.target or f.port))
            self.log.append((site, f"scheduled.{f.action}", dict(header)))
            logger.info(
                "chaos: scheduled %s at decode step %d (peer %s)",
                f.action, f._seen, conn.peer,
            )
            if f.action == "crash":
                cb = self.schedule._crash_cbs.get(f.target or "")
                if cb is None:
                    raise RuntimeError(
                        f"scheduled crash target {f.target!r} has no "
                        "bound callback (FaultSchedule.bind_crash)"
                    )
                # crash() itself ledgers the server.crash fault
                cb()
                verdict = "drop"
                continue
            ledger.fault(f"wire.scheduled.{f.action}")
            if f.action == "partition":
                self._partition(conn)
                verdict = "drop"
            elif f.action == "corrupt":
                self._corrupt(header, blobs)
            elif f.action == "delay":
                await asyncio.sleep(f.delay_s)
            elif f.action in ("stall", "drop"):
                verdict = "drop"
            elif f.action in ("reset", "close"):
                await self._kill(conn, abort=f.action == "reset")
                raise InjectedFault(f"injected scheduled {f.action}")
            else:
                raise ValueError(f"unknown scheduled action {f.action!r}")
        return verdict

    async def _apply(self, conn, rule: FaultRule, header: dict) -> None:
        if rule.action == "delay":
            await asyncio.sleep(rule.delay_s)
            return
        if rule.action == "stall":
            # a wedged writer: hold the frame until the connection dies
            logger.info("chaos: stalling send to %s", conn.peer)
            await conn._closed.wait()
            raise InjectedFault("injected send stall")
        if rule.action in ("reset", "close"):
            logger.info(
                "chaos: %s connection to %s on %s frame", rule.action,
                conn.peer, header.get("t"),
            )
            await self._kill(conn, abort=rule.action == "reset")
            raise InjectedFault(f"injected connection {rule.action}")

    def _corrupt(self, header: dict, blobs: list | None) -> None:
        """Byzantine payload corruption: decode the first float tensor in
        the frame, perturb it with a seeded pick of NaN-poison / ×64 scale
        / exponent bit-flip, and re-serialize. The frame stays well-formed
        (valid header, codec, sizes) — only the numbers lie. Non-float
        frames are left untouched (corrupting int token ids would be
        undetectable by activation checks and is a different failure
        class) — EXCEPT compile-artifact transfers, whose raw uint8
        blobs get a single bit flipped: the blake2b digest check on
        install must convict it, exactly like a corrupt span output must
        be convicted by the integrity layer."""
        tms = header.get("tm") or []
        if not tms or not blobs:
            return
        from bloombee_tpu.wire import tensor_codec

        try:
            meta = tensor_codec.TensorMeta.from_wire(tms[0])
            arr = tensor_codec.deserialize_tensor(meta, blobs[0]).copy()
        except Exception:  # pragma: no cover - malformed frames ship as-is
            return
        is_float = np.issubdtype(np.dtype(arr.dtype), np.floating) or (
            np.dtype(arr.dtype) == np.dtype(ml_dtypes.bfloat16)
        )
        if arr.size == 0:
            return
        flat = arr.reshape(-1)
        idx = self.rng.randrange(flat.size)
        if not is_float:
            if not _is_artifact_transfer(header):
                return
            flat.view(np.uint8)[
                idx * arr.dtype.itemsize
            ] ^= 0x40
        else:
            mode = ("nan", "scale", "bitflip")[self.rng.randrange(3)]
            if mode == "nan":
                flat[idx] = float("nan")
            elif mode == "scale":
                np.multiply(arr, arr.dtype.type(64), out=arr)
            else:
                # flip the top exponent bit of one element via its raw
                # bytes — the classic single-bit memory fault
                view = flat.view(np.uint8)
                byte = idx * arr.dtype.itemsize + (arr.dtype.itemsize - 1)
                view[byte] ^= 0x40
        m, b = tensor_codec.serialize_tensor(arr, compression=True)
        tms[0] = m.to_wire()
        blobs[0] = b
        header["tm"] = tms

    @staticmethod
    def _partition(conn) -> None:
        """Mark the connection blackholed: the flag lives on the Connection
        (not the plan) so one marking silences both directions as observed
        by BOTH endpoints — our sends never reach the wire's effects and
        every arriving frame is swallowed before dispatch. No FIN/RST is
        ever generated; only a keepalive timeout can notice."""
        logger.info("chaos: partitioning connection to %s", conn.peer)
        conn._bbtpu_partitioned = True

    @staticmethod
    async def _kill(conn, abort: bool) -> None:
        try:
            if abort:
                transport = conn.writer.transport
                if transport is not None:
                    transport.abort()
            else:
                conn.writer.close()
        except Exception:
            pass

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        """Build a plan from the BBTPU_CHAOS_* knobs; None when chaos is
        off. A BBTPU_CHAOS_SCHEDULE alone arms the plan (deterministic
        scripts should not require enabling the probabilistic machinery)."""
        schedule = FaultSchedule.from_env()
        if not env.get("BBTPU_CHAOS") and schedule is None:
            return None
        plan = cls(seed=env.get("BBTPU_CHAOS_SEED"), schedule=schedule)
        if not env.get("BBTPU_CHAOS"):
            return plan
        delay_p = env.get("BBTPU_CHAOS_DELAY_P")
        if delay_p > 0:
            plan.add(FaultRule(
                site="send", action="delay", prob=delay_p,
                delay_s=env.get("BBTPU_CHAOS_DELAY_S"),
            ))
        reset_p = env.get("BBTPU_CHAOS_RESET_P")
        if reset_p > 0:
            plan.add(FaultRule(site="send", action="reset", prob=reset_p))
        stall_p = env.get("BBTPU_CHAOS_STALL_P")
        if stall_p > 0:
            plan.add(FaultRule(site="read", action="stall", prob=stall_p))
        partition_p = env.get("BBTPU_CHAOS_PARTITION_P")
        if partition_p > 0:
            plan.add(FaultRule(
                site="send", action="partition", prob=partition_p,
            ))
        corrupt_p = env.get("BBTPU_CHAOS_CORRUPT_P")
        if corrupt_p > 0:
            # only span-output step replies ("sitem" frames whose meta
            # carries compute timing) are corrupted: a process-wide plan is
            # shared by in-proc client AND servers, and corrupting a
            # client->server prefill frame would poison server KV in a way
            # no client-side check can see (the lie becomes the ground
            # truth both replicas agree on)
            plan.add(FaultRule(
                site="send", action="corrupt", method="sitem",
                prob=corrupt_p, predicate=_is_span_output_reply,
            ))
        return plan


def _is_span_output_reply(header: dict) -> bool:
    """True for stream items that carry a span-output tensor (step replies
    stamp t_compute_ms into their meta; acks and client-side frames don't)."""
    meta = header.get("meta") or {}
    return bool(header.get("tm")) and "t_compute_ms" in meta


def _is_artifact_transfer(header: dict) -> bool:
    """True for compile-artifact frames (artifact_get/put requests and
    their blob-carrying replies — both stamp "artifact" into their meta,
    since unary "res" frames carry no method name to match on). Chaos
    rules use this to corrupt/stall/kill the artifact stream without
    touching the inference path."""
    meta = header.get("meta") or {}
    return bool(meta.get("artifact"))


_active_plan: FaultPlan | None = None
_env_checked = False


def set_plan(plan: FaultPlan | None) -> None:
    """Install the process-wide plan (tests). None disarms injection."""
    global _active_plan, _env_checked
    _active_plan = plan
    _env_checked = True  # an explicit plan overrides the env knobs


def get_plan() -> FaultPlan | None:
    """Plan consulted by new Connections; lazily built from env once."""
    global _active_plan, _env_checked
    if not _env_checked:
        _env_checked = True
        _active_plan = FaultPlan.from_env()
    return _active_plan
