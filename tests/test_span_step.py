"""Span step parity: paged prefill + decode vs dense HF reference.

The TPU-native analogue of /root/reference/tests/test_block_exact_match.py's
step-wise inference check (atol 1e-3), across a whole span with the paged KV
arena instead of dense concat caches.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.kv.cache_manager import CacheManager
from bloombee_tpu.models.llama.block import HF_BLOCK_KEYS, convert_hf_block_params
from bloombee_tpu.models.llama.config import llama_spec_from_hf
from bloombee_tpu.runtime.executor import SpanExecutor
from bloombee_tpu.utils.tree import stack_params


@pytest.fixture(scope="module")
def setup():
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=256,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    spec = llama_spec_from_hf(config)
    layers = []
    for layer in model.model.layers:
        sd = layer.state_dict()
        layers.append(
            convert_hf_block_params({k: sd[k].numpy() for k in HF_BLOCK_KEYS})
        )
    params = stack_params(layers)
    return model, config, spec, params


def hf_span_forward(model, hidden_t: torch.Tensor) -> np.ndarray:
    """Dense full-sequence forward through all decoder layers (no norm/head)."""
    t = hidden_t.shape[1]
    position_ids = torch.arange(t).unsqueeze(0).expand(hidden_t.shape[0], -1)
    cos, sin = model.model.rotary_emb(hidden_t, position_ids)
    h = hidden_t
    with torch.no_grad():
        for layer in model.model.layers:
            out = layer(h, position_embeddings=(cos, sin), attention_mask=None)
            h = out[0] if isinstance(out, tuple) else out
    return h.numpy()


def make_executor(spec, params, **kw):
    manager = CacheManager(
        num_layers=spec.num_hidden_layers,
        num_pages=32,
        page_size=4,
        n_kv_heads=spec.num_key_value_heads,
        head_dim=spec.head_dim,
        dtype=jnp.float32,
    )
    ex = SpanExecutor(
        params, spec, manager, compute_dtype=jnp.float32, **kw
    )
    return manager, ex


def test_prefill_then_decode_matches_dense(setup):
    model, config, spec, params = setup
    b, total, prefill = 2, 12, 7
    torch.manual_seed(3)
    hidden = torch.randn(b, total, config.hidden_size)
    ref = hf_span_forward(model, hidden)

    manager, ex = make_executor(spec, params)

    async def run():
        async with manager.allocate(b, 32) as handle:
            out_pre = ex.prefill(handle, hidden[:, :prefill].numpy())
            np.testing.assert_allclose(
                out_pre, ref[:, :prefill], atol=1e-3, rtol=1e-3
            )
            for i in range(prefill, total):
                out_i = ex.decode(handle, hidden[:, i : i + 1].numpy())
                np.testing.assert_allclose(
                    out_i, ref[:, i : i + 1], atol=1e-3, rtol=1e-3,
                    err_msg=f"decode step {i}",
                )
            assert manager.context_lens(handle).tolist() == [total, total]

    asyncio.run(run())


def test_chunked_prefill_matches(setup):
    model, config, spec, params = setup
    b, total = 1, 11
    torch.manual_seed(4)
    hidden = torch.randn(b, total, config.hidden_size)
    ref = hf_span_forward(model, hidden)

    manager, ex = make_executor(spec, params, max_chunk_tokens=4)

    async def run():
        async with manager.allocate(b, 16) as handle:
            out = ex.prefill(handle, hidden.numpy())
            np.testing.assert_allclose(out, ref, atol=1e-3, rtol=1e-3)

    asyncio.run(run())


def test_non_pow2_batch_padding(setup):
    model, config, spec, params = setup
    b, total = 3, 6
    torch.manual_seed(5)
    hidden = torch.randn(b, total, config.hidden_size)
    ref = hf_span_forward(model, hidden)

    manager, ex = make_executor(spec, params)

    async def run():
        async with manager.allocate(b, 8) as handle:
            out = ex.prefill(handle, hidden.numpy())
            np.testing.assert_allclose(out, ref, atol=1e-3, rtol=1e-3)

    asyncio.run(run())


def test_speculative_decode_rollback(setup):
    """Write speculative tokens uncommitted, roll back, decode the true token —
    result must match the no-speculation path (paged commit/rollback with the
    arena: reference paged_kv spec-dec routing tests)."""
    model, config, spec, params = setup
    b, prefill = 1, 5
    torch.manual_seed(6)
    hidden = torch.randn(b, prefill + 1, config.hidden_size)
    ref = hf_span_forward(model, hidden)

    manager, ex = make_executor(spec, params)

    async def run():
        async with manager.allocate(b, 16) as handle:
            ex.prefill(handle, hidden[:, :prefill].numpy())
            # speculative garbage tokens, uncommitted
            garbage = np.random.default_rng(0).normal(
                size=(b, 3, config.hidden_size)
            ).astype(np.float32)
            ex.decode(handle, garbage, commit=False)
            assert manager.context_lens(handle).tolist() == [prefill + 3]
            manager.rollback(handle)
            assert manager.context_lens(handle).tolist() == [prefill]
            out = ex.decode(handle, hidden[:, prefill:].numpy())
            np.testing.assert_allclose(
                out, ref[:, prefill:], atol=1e-3, rtol=1e-3
            )

    asyncio.run(run())


def test_packed_payload_bitcast_roundtrip_bf16_and_f32():
    """pack_step_payload's single-buffer bitcast must round-trip exactly on
    the device for BOTH lane widths: uint16 (bf16 serving, the production
    wire) and uint32 (fp32 parity serving)."""
    import functools

    import jax
    import ml_dtypes
    from jax import lax

    from bloombee_tpu.runtime.step import pack_step_payload

    rng = np.random.default_rng(0)
    plan = rng.integers(-(2**31), 2**31 - 1, size=(57,), dtype=np.int32)

    for np_dt, jnp_dt in ((ml_dtypes.bfloat16, jnp.bfloat16),
                          (np.float32, jnp.float32)):
        h = rng.standard_normal((2, 3, 8)).astype(np_dt)
        payload = pack_step_payload(h, plan)

        @functools.partial(jax.jit, static_argnames=("n_h",))
        def unpack(p, n_h):
            if p.dtype == jnp.uint16:
                hid = lax.bitcast_convert_type(p[:n_h], jnp.bfloat16)
                pl_ = lax.bitcast_convert_type(
                    p[n_h:].reshape(-1, 2), jnp.int32
                )
            else:
                hid = lax.bitcast_convert_type(p[:n_h], jnp.float32)
                pl_ = lax.bitcast_convert_type(p[n_h:], jnp.int32)
            return hid, pl_

        hid, pl_ = unpack(jnp.asarray(payload), n_h=h.size)
        assert np.asarray(hid).view(np.uint8).tobytes() == h.tobytes()
        np.testing.assert_array_equal(np.asarray(pl_), plan)


def test_span_decode_bf16_compute_runs_packed_path():
    """The bf16 (uint16-lane) packed path through the real executor: prefill
    + decode produce finite bf16 outputs."""
    import ml_dtypes

    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec

    spec = ModelSpec(
        family="llama", hidden_size=32, intermediate_size=64,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        num_hidden_layers=2, vocab_size=64,
    )
    import jax

    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.bfloat16)
         for i in range(2)]
    )

    async def run():
        manager = CacheManager(
            num_layers=2, num_pages=16, page_size=4, n_kv_heads=2,
            head_dim=8, dtype=jnp.bfloat16,
        )
        ex = SpanExecutor(params, spec, manager,
                          compute_dtype=jnp.bfloat16)
        rng = np.random.default_rng(0)
        async with manager.allocate(2, 12) as handle:
            out = ex.prefill(
                handle, rng.standard_normal((2, 6, 32)).astype(np.float32)
            )
            assert out.dtype == ml_dtypes.bfloat16
            assert np.isfinite(out.astype(np.float32)).all()
            out = ex.decode(
                handle, rng.standard_normal((2, 1, 32)).astype(np.float32)
            )
            assert out.dtype == ml_dtypes.bfloat16
            assert np.isfinite(out.astype(np.float32)).all()

    asyncio.run(run())


def test_attn_sparsity_topk():
    """FlexGen Policy.attn_sparsity analog: attend_paged with attn_topk keeps
    only the top-k keys per query (plus the query's own position) and
    renormalizes; sparsity=1 is exactly dense, and a numpy reference pins
    the top-k rule."""
    import jax
    import jax.numpy as jnp

    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.layer_body import attend_paged

    spec = ModelSpec(
        family="llama", hidden_size=32, intermediate_size=64,
        num_attention_heads=2, num_key_value_heads=2, head_dim=16,
        num_hidden_layers=1, vocab_size=32,
    )
    rng = np.random.default_rng(0)
    B, T, S, H, hd = 2, 1, 12, 2, 16
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, H, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, H, hd)), jnp.float32)
    lens = jnp.asarray([10, 7], jnp.int32)
    q_pos = (lens - 1)[:, None]

    dense = np.asarray(
        attend_paged(spec, q, k, v, q_pos, lens, None, jnp.int32(0))
    )
    same = np.asarray(
        attend_paged(spec, q, k, v, q_pos, lens, None, jnp.int32(0),
                     attn_topk=S)
    )
    np.testing.assert_allclose(same, dense, atol=1e-6)

    topk = 3
    got = np.asarray(
        attend_paged(spec, q, k, v, q_pos, lens, None, jnp.int32(0),
                     attn_topk=topk)
    )
    # numpy reference: mask invalid/future, keep top-k logits + own position
    scale = hd ** -0.5
    qf, kf, vf = (np.asarray(x, np.float32) for x in (q, k, v))
    want = np.zeros_like(got)
    for b in range(B):
        L = int(lens[b])
        own = L - 1
        for h in range(H):
            lg = (qf[b, 0, h] * scale) @ kf[b, :, h].T
            lg[L:] = -np.inf
            kept = np.argsort(lg)[-topk:]
            keep = set(kept.tolist()) | {own}
            lg2 = np.full(S, -np.inf)
            for i in keep:
                lg2[i] = lg[i]
            w = np.exp(lg2 - np.max(lg2))
            w = w / w.sum()
            want[b, 0, h] = w @ vf[b, :, h]
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_attn_sparsity_executor_smoke():
    """attn_sparsity<1 serves finite outputs and differs from dense (it is
    approximate), while sparsity=1.0 is the exact default path."""
    import asyncio

    import jax
    import jax.numpy as jnp

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_hidden_layers=2, vocab_size=64,
    )
    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.float32)
         for i in range(2)]
    )
    rng = np.random.default_rng(1)
    prefill = (rng.standard_normal((1, 30, 64)) * 0.1).astype(np.float32)
    step = (rng.standard_normal((1, 1, 64)) * 0.1).astype(np.float32)

    async def run(sparsity):
        manager = CacheManager(
            num_layers=2, num_pages=16, page_size=4, n_kv_heads=2,
            head_dim=16, dtype=jnp.float32,
        )
        ex = SpanExecutor(params, spec, manager, compute_dtype=jnp.float32,
                          attn_sparsity=sparsity)
        async with manager.allocate(1, 40) as handle:
            ex.prefill(handle, prefill)
            return np.asarray(ex.decode(handle, step))

    dense = asyncio.run(run(1.0))
    sparse = asyncio.run(run(0.25))
    assert np.isfinite(sparse).all()
    assert np.abs(sparse - dense).max() > 1e-6  # actually approximated
