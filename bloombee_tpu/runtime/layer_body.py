"""Family-generic transformer layer body for the paged span step.

One implementation covers every supported family via ModelSpec switches
(all resolved at trace time — the compiled program contains no branches):

- llama / qwen3 / mixtral: RMSNorm, rotary, GQA, gated-SiLU or MoE MLP,
  optional per-head q/k norm (qwen3)
- gemma2-style: sandwich norms, gated tanh-GELU MLP, attention logit
  soft-capping, alternating sliding-window layers (per-layer window rides
  the scan)
- bloom: LayerNorm(+bias), ALiBi instead of rotary, plain 4h GELU MLP,
  biased projections
- falcon: LayerNorm, rotary, MQA/GQA, parallel attention+MLP residual

Replaces the reference's per-family Wrapped*Block zoo
(/root/reference/src/bloombee/models/*/block.py) — there the per-family code
wraps HF torch modules; here the differences are data (spec fields + param
keys), so every family runs through the same scan/paged-attention machinery.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bloombee_tpu.kv.arena import arena_write, gather_pages
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.models.wquant import maybe_dequantize
from bloombee_tpu.ops import apply_rotary, rms_norm, silu_mlp
from bloombee_tpu.ops.alibi import alibi_slopes
from bloombee_tpu.ops.attention import NEG_INF, repeat_kv
from bloombee_tpu.ops.moe import moe_mlp
from bloombee_tpu.ops.norms import layer_norm


def _norm(x, params, key, spec):
    if spec.norm_type == "ln":
        return layer_norm(
            x, params[key], params.get(f"{key}_bias"), spec.rms_norm_eps
        )
    return rms_norm(x, params[key], spec.rms_norm_eps)


def _proj(x, params, key, lora=None):
    # quantized projections dequantize here; XLA fuses the convert+scale
    # into the matmul's operand read (no dense copy lands in HBM)
    y = x @ maybe_dequantize(params[key], x.dtype)
    b = params.get(f"{key.removesuffix('_proj')}_bias")
    if b is not None:
        y = y + b
    if lora is not None and key in lora:
        # per-request LoRA (reference utils/peft.py LoraLinear forward):
        # y += (x A) B with the alpha/r scaling folded into B at load.
        # Factors stay unmerged so one base weight serves every adapter.
        f = lora[key]
        y = y + (x @ f["a"].astype(x.dtype)) @ f["b"].astype(x.dtype)
    return y


def _mlp(x, params, spec, lora=None):
    mlp_lora = lora is not None and any(
        k in lora for k in ("gate_proj", "up_proj", "down_proj")
    )
    if mlp_lora and not spec.num_experts and spec.mlp_type == "silu":
        # lora-aware gated-SiLU composition (the fused silu_mlp takes raw
        # matrices, so the adapterized path spells it out)
        g = _proj(x, params, "gate_proj", lora)
        u = _proj(x, params, "up_proj", lora)
        return _proj(jax.nn.silu(g) * u, params, "down_proj", lora)
    if spec.num_experts:
        return moe_mlp(
            x,
            params["router"],
            maybe_dequantize(params["experts_gate"], x.dtype),
            maybe_dequantize(params["experts_up"], x.dtype),
            maybe_dequantize(params["experts_down"], x.dtype),
            spec.num_experts_per_tok,
            pre_softmax=spec.moe_pre_softmax,
            norm_topk=spec.moe_norm_topk,
        )
    if spec.mlp_type == "silu":
        return silu_mlp(
            x,
            maybe_dequantize(params["gate_proj"], x.dtype),
            maybe_dequantize(params["up_proj"], x.dtype),
            maybe_dequantize(params["down_proj"], x.dtype),
        )
    if spec.mlp_type == "gelu_tanh_gated":
        g = _proj(x, params, "gate_proj", lora)
        u = _proj(x, params, "up_proj", lora)
        return _proj(jax.nn.gelu(g, approximate=True) * u, params,
                     "down_proj", lora)
    # plain 4h GELU: "gelu" = exact/erf (falcon), "gelu_tanh" = tanh (bloom)
    h = jax.nn.gelu(
        _proj(x, params, "up_proj", lora), approximate=spec.mlp_type != "gelu"
    )
    return _proj(h, params, "down_proj", lora)


def attn_scale(spec: ModelSpec) -> float:
    return (
        spec.attention_multiplier
        if spec.attention_multiplier is not None
        else spec.head_dim**-0.5
    )


def attend_paged(
    spec: ModelSpec,
    q: jax.Array,  # [B, T, H, hd]
    k_ctx: jax.Array,  # [B, S, Hkv, hd]
    v_ctx: jax.Array,
    q_positions: jax.Array,  # [B, T]
    total_lens: jax.Array,  # [B]
    tree_mask: jax.Array | None,
    window,  # traced int32 scalar; 0 = full attention
    attn_topk: int = 0,  # >0: keep only the top-k keys per query (FlexGen
    # Policy.attn_sparsity, pytorch_backend.py:564-638 _sparse_attention_value
    # — there the top-k of past weights plus the newest token; here the
    # equivalent pre-softmax mask, so kept weights renormalize)
) -> jax.Array:
    b, t = q.shape[:2]
    s = k_ctx.shape[1]
    key_pos = jnp.arange(s, dtype=jnp.int32)[None, None, :]  # [1, 1, S]
    q_pos = q_positions[:, :, None]  # [B, T, 1]
    valid = key_pos < total_lens[:, None, None]
    mask = valid & (key_pos <= q_pos)
    mask &= (window <= 0) | (key_pos > (q_pos - window))
    if tree_mask is not None:
        # current step's tokens sit at cache positions total-T..total-1;
        # their mutual visibility comes from the tree mask
        # (reference: backend.py:596-652)
        step_start = (total_lens - t)[:, None, None]
        in_step = (key_pos >= step_start) & (key_pos < total_lens[:, None, None])
        rel = jnp.clip(key_pos - step_start, 0, t - 1)
        tree_on_keys = jnp.take_along_axis(
            tree_mask, jnp.broadcast_to(rel, (b, t, s)), axis=2
        )
        mask = jnp.where(in_step, tree_on_keys & valid, mask)

    n_rep = q.shape[2] // k_ctx.shape[2]
    k_r = repeat_kv(k_ctx, n_rep)
    v_r = repeat_kv(v_ctx, n_rep)
    scale = attn_scale(spec)
    logits = jnp.einsum("bthd,bshd->bhts", q, k_r).astype(jnp.float32) * scale
    if spec.attn_logit_softcap:
        logits = (
            jnp.tanh(logits / spec.attn_logit_softcap) * spec.attn_logit_softcap
        )
    if spec.alibi:
        slopes = jnp.asarray(alibi_slopes(spec.num_attention_heads))
        logits = logits + slopes[None, :, None, None] * key_pos[:, :, None, :].astype(jnp.float32)
    logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
    if attn_topk and attn_topk < s:
        kth = jax.lax.top_k(logits, attn_topk)[0][..., -1:]  # [B,H,T,1]
        own = (key_pos == q_pos)[:, None, :, :]  # the newest token survives
        logits = jnp.where((logits >= kth) | own, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v_r)


def layer_body(
    spec: ModelSpec,
    page_size: int,
    hidden: jax.Array,  # [B, T, D]
    params: dict,  # one layer's params
    k_slab: jax.Array,  # [S_tot, Hkv, hd]
    v_slab: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    slots: jax.Array,
    page_table: jax.Array,
    q_positions: jax.Array,
    total_lens: jax.Array,
    tree_mask: jax.Array | None,
    window,  # traced scalar
    use_flash: bool = False,  # static: executor's shape heuristic said yes
    use_paged: bool = False,  # static: T=1 decode via the paged kernel
    lora: dict | None = None,  # this layer's per-request LoRA factors
    attn_topk: int = 0,  # sparse attention (executor disables the Pallas
    # kernels when this is on)
    t_real: int | None = None,  # real (unpadded) step tokens when T is a
    # padded bucket (the chunk kernel needs it to place query positions)
):
    b, t, d = hidden.shape
    h_heads, kv_heads, hd = (
        spec.num_attention_heads,
        spec.num_key_value_heads,
        spec.head_dim,
    )
    x = _norm(hidden, params, "input_layernorm", spec)
    q = _proj(x, params, "q_proj", lora).reshape(b, t, h_heads, hd)
    k = _proj(x, params, "k_proj", lora).reshape(b, t, kv_heads, hd)
    if spec.k_eq_v:
        # gemma-4 full-attention layers alias V to K (one shared
        # projection; reference gemma4/block.py attention_k_eq_v)
        v = k
    else:
        v = _proj(x, params, "v_proj", lora).reshape(b, t, kv_heads, hd)
    if spec.qk_norm:
        q = rms_norm(q, params["q_norm"], spec.rms_norm_eps)
        k = rms_norm(k, params["k_norm"], spec.rms_norm_eps)
    if not spec.alibi:
        q, k = apply_rotary(q, k, cos, sin)

    k_slab, v_slab = arena_write(
        k_slab, v_slab, slots,
        k.reshape(b * t, kv_heads, hd), v.reshape(b * t, kv_heads, hd),
    )
    if use_paged:
        # the Pallas kernels stream K/V pages straight from the arena
        # (page table as scalar prefetch) — no gathered [B, S, Hkv, hd]
        # context buffer in HBM at all. T==1: decode kernel (int4 arenas
        # dequantize in-kernel); T>1: chunk kernel covering tree-verify
        # steps (tree mask applied in-kernel) and short multi-token
        # chunks. Eligibility (no alibi/softcap, T*H VMEM budget,
        # tree+window excluded) was checked host-side; sliding windows
        # ride in as a per-layer traced scalar.
        from bloombee_tpu.kv.quant import QuantSlab
        from bloombee_tpu.ops.pallas.paged_attention import (
            paged_chunk_attention,
            paged_decode_attention,
            paged_decode_attention_int4,
        )

        interpret = jax.default_backend() != "tpu"
        if t == 1:
            kernel = (
                paged_decode_attention_int4
                if isinstance(k_slab, QuantSlab)
                else paged_decode_attention
            )
            attn = kernel(
                q[:, 0], k_slab, v_slab, page_table, total_lens,
                page_size=page_size, scale=attn_scale(spec),
                # Mosaic only exists on TPU; any other backend that
                # reaches here (BBTPU_PAGED_INTERPRET) interprets
                interpret=interpret,
                window=window,  # per-layer traced scalar (0 = full)
            )[:, None]  # [B, 1, H, hd]
        else:
            attn = paged_chunk_attention(
                q, k_slab, v_slab, page_table, total_lens,
                page_size=page_size, tree_mask=tree_mask,
                scale=attn_scale(spec), interpret=interpret,
                window=window, has_tree=tree_mask is not None,
                t_real=t_real,
            )
        attn_out = _proj(
            attn.reshape(b, t, h_heads * hd), params, "o_proj", lora
        )
        return _finish_layer(
            spec, params, hidden, x, attn_out, k_slab, v_slab, lora
        )
    k_ctx = gather_pages(k_slab, page_table, page_size).astype(hidden.dtype)
    v_ctx = gather_pages(v_slab, page_table, page_size).astype(hidden.dtype)

    if use_flash:
        # long-context prefill: the Pallas kernel streams K/V tiles through
        # VMEM instead of materializing [B,H,T,S] logits in HBM. Eligibility
        # (no tree/window/alibi/softcap, T>=128) was checked host-side by
        # the executor; per-row starts/lens ride in as traced vectors, so
        # MIXED-length batches (multi-turn session prefill) engage flash
        # too, with the lens mask hiding each row's page-padded tail.
        from bloombee_tpu.ops.pallas.flash_attention import flash_attention

        attn = flash_attention(
            q, k_ctx, v_ctx, causal=True, scale=attn_scale(spec),
            starts=q_positions[:, 0], lens=total_lens,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        attn = attend_paged(
            spec, q, k_ctx, v_ctx, q_positions, total_lens, tree_mask,
            window, attn_topk,
        )
    attn_out = _proj(attn.reshape(b, t, h_heads * hd), params, "o_proj", lora)
    return _finish_layer(
        spec, params, hidden, x, attn_out, k_slab, v_slab, lora
    )


def attend_ragged(
    spec: ModelSpec,
    q: jax.Array,  # [R, H, hd] — ragged token rows across ALL members
    k_ctx: jax.Array,  # [B, S, Hkv, hd] — every member's gathered context
    v_ctx: jax.Array,
    q_pos: jax.Array,  # [R] context position per token
    q_seq: jax.Array,  # [R] owning sequence per token (>= B = padding)
    total_lens: jax.Array,  # [B]
    window,  # traced int32 scalar; 0 = full attention
    nt: jax.Array | None = None,  # [B] in-step token count per sequence
    tree_rows: jax.Array | None = None,  # [R, t_max] in-step visibility
) -> jax.Array:  # [R, H, hd]
    """Dense fallback for the ragged mixed-batch step: every token row
    attends the full [B, S] cross-session context and masks everything it
    doesn't own. Handles the kernel-ineligible configs (ALiBi, logit
    soft-cap, quantized arenas via gather_pages dequant) so those models
    still get the single fused dispatch. The x B masked logits columns are
    the fallback's price; padding rows (q_seq >= B) are fully masked and
    softmax to garbage that the executor slices away.

    (nt, tree_rows) switch the causal term into ragged TREE-verify
    semantics: sequence b's last nt[b] storage slots hold this step's
    speculative tree tokens, committed keys (storage pos < lens - nt) stay
    fully visible, and row i sees in-step slot m of its own sequence iff
    tree_rows[i, m]. Causality between in-step tokens is entirely encoded
    by tree_rows (ancestor-or-self), since depth positions repeat across
    sibling branches."""
    r, h, hd = q.shape
    b, s = k_ctx.shape[:2]
    key_pos = jnp.arange(s, dtype=jnp.int32)[None, None, :]  # [1, 1, S]
    seq_ids = jnp.arange(b, dtype=jnp.int32)[None, :, None]  # [1, B, 1]
    qp = q_pos[:, None, None]  # [R, 1, 1]
    own = (q_seq[:, None, None] == seq_ids) & (
        key_pos < total_lens[None, :, None]
    )
    if tree_rows is None:
        mask = own & (key_pos <= qp)
        mask &= (window <= 0) | (key_pos > (qp - window))
    else:
        t_max = tree_rows.shape[1]
        step_start = (total_lens - nt)[None, :, None]  # [1, B, 1]
        m = key_pos - step_start  # [1, B, S] in-step slot index (or < 0)
        mc = jnp.clip(m[0], 0, t_max - 1)  # [B, S]
        vis = tree_rows[:, mc] > 0  # [R, B, S]
        in_step = (m >= 0) & (key_pos < total_lens[None, :, None])
        mask = own & ((key_pos < step_start) | (in_step & vis))

    n_rep = h // k_ctx.shape[2]
    k_r = repeat_kv(k_ctx, n_rep)  # [B, S, H, hd]
    v_r = repeat_kv(v_ctx, n_rep)
    scale = attn_scale(spec)
    logits = jnp.einsum("rhd,bshd->rhbs", q, k_r).astype(jnp.float32) * scale
    if spec.attn_logit_softcap:
        logits = (
            jnp.tanh(logits / spec.attn_logit_softcap)
            * spec.attn_logit_softcap
        )
    if spec.alibi:
        slopes = jnp.asarray(alibi_slopes(spec.num_attention_heads))
        logits = logits + (
            slopes[None, :, None, None] * key_pos[None].astype(jnp.float32)
        )
    logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
    # softmax over the FLATTENED cross-session key axis: each row's mask
    # confines its probability mass to its own sequence's keys
    probs = jax.nn.softmax(
        logits.reshape(r, h, b * s), axis=-1
    ).astype(q.dtype)
    return jnp.einsum("rhs,shd->rhd", probs, v_r.reshape(b * s, h, hd))


def layer_body_ragged(
    spec: ModelSpec,
    page_size: int,
    hidden: jax.Array,  # [1, R, D] — every member's tokens, ragged-packed
    params: dict,  # one layer's params
    k_slab: jax.Array,  # [S_tot, Hkv, hd]
    v_slab: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    slots: jax.Array,  # [R] (padding rows scatter out-of-bounds and drop)
    page_table: jax.Array,  # [B, NP]
    q_positions: jax.Array,  # [1, R]
    total_lens: jax.Array,  # [B]
    q_seq: jax.Array,  # [R] owning sequence per token
    window,  # traced per-layer scalar
    use_kernel: bool = False,  # static: ragged Pallas kernel vs dense
    lora: dict | None = None,
    nt: jax.Array | None = None,  # [B] in-step token counts (tree groups)
    tree_rows: jax.Array | None = None,  # [R, t_max] in-step visibility
):
    """layer_body for the ragged mixed-batch step: one [1, R, D] row-major
    pack of N decode tokens plus one prefill chunk's tokens — or, when
    (nt, tree_rows) are given, N sessions' speculative TREE rows verifying
    in one dispatch. Projections, rotary, and the arena scatter are
    position-wise, so they need no per-member structure — only attention
    does, and it gets it from (q_seq, q_positions) per row instead of
    layer_body's block-uniform (B, T)."""
    _, r, d = hidden.shape
    h_heads, kv_heads, hd = (
        spec.num_attention_heads,
        spec.num_key_value_heads,
        spec.head_dim,
    )
    x = _norm(hidden, params, "input_layernorm", spec)
    q = _proj(x, params, "q_proj", lora).reshape(1, r, h_heads, hd)
    k = _proj(x, params, "k_proj", lora).reshape(1, r, kv_heads, hd)
    if spec.k_eq_v:
        v = k
    else:
        v = _proj(x, params, "v_proj", lora).reshape(1, r, kv_heads, hd)
    if spec.qk_norm:
        q = rms_norm(q, params["q_norm"], spec.rms_norm_eps)
        k = rms_norm(k, params["k_norm"], spec.rms_norm_eps)
    if not spec.alibi:
        q, k = apply_rotary(q, k, cos, sin)

    k_slab, v_slab = arena_write(
        k_slab, v_slab, slots,
        k.reshape(r, kv_heads, hd), v.reshape(r, kv_heads, hd),
    )
    from bloombee_tpu.kv.quant import QuantSlab

    if use_kernel and not isinstance(k_slab, QuantSlab):
        from bloombee_tpu.ops.pallas.paged_attention import (
            paged_ragged_attention,
        )

        attn = paged_ragged_attention(
            q[0], k_slab, v_slab, page_table, total_lens,
            q_seq, q_positions[0],
            page_size=page_size, scale=attn_scale(spec),
            interpret=jax.default_backend() != "tpu",
            window=window, nt=nt, tree_rows=tree_rows,
            has_tree=tree_rows is not None,
        )[None]
    else:
        k_ctx = gather_pages(
            k_slab, page_table, page_size
        ).astype(hidden.dtype)
        v_ctx = gather_pages(
            v_slab, page_table, page_size
        ).astype(hidden.dtype)
        attn = attend_ragged(
            spec, q[0], k_ctx, v_ctx, q_positions[0], q_seq, total_lens,
            window, nt=nt, tree_rows=tree_rows,
        )[None]
    attn_out = _proj(
        attn.reshape(1, r, h_heads * hd), params, "o_proj", lora
    )
    return _finish_layer(
        spec, params, hidden, x, attn_out, k_slab, v_slab, lora
    )


def dense_unsupported(spec: ModelSpec) -> str | None:
    """Why a family can't run the cache-returning DENSE block forward
    (drafter path); None when it can. These are attend-injection limits:
    the caller supplies the attention fn, so position-bias (ALiBi),
    sliding windows, and logit soft-caps would silently drop."""
    if spec.alibi:
        return "ALiBi bias lives inside attention"
    if spec.layer_types and "sliding" in spec.layer_types:
        return "sliding-window masks live inside attention"
    if spec.attn_logit_softcap:
        return "attention logit soft-cap lives inside attention"
    if spec.heterogeneous:
        return "heterogeneous head_dim layers"
    return None


def dense_block_forward(
    params: dict,
    spec: ModelSpec,
    hidden: jax.Array,  # [B, T, D]
    cos: jax.Array,
    sin: jax.Array,
    attend,  # (q, k, v) -> (attn_out [B, T, H, hd], aux)
):
    """Family-generic DENSE block forward with caller-supplied attention —
    the client-side analog of layer_body for code that manages its own KV
    (the speculative drafter; reference spec_decoding_drafter.py:67-110
    drives HF models the same way). Same spec switches as layer_body:
    norm types + biases, qk-norm, parallel-attn residual, sandwich norms,
    silu/gelu/MoE MLPs. Returns (hidden, (k, v))."""
    reason = dense_unsupported(spec)
    if reason is not None:
        raise NotImplementedError(
            f"dense block forward doesn't cover family {spec.family!r}: "
            f"{reason}"
        )
    b, t, d = hidden.shape
    h_heads, kv_heads, hd = (
        spec.num_attention_heads,
        spec.num_key_value_heads,
        spec.head_dim,
    )
    x = _norm(hidden, params, "input_layernorm", spec)
    q = _proj(x, params, "q_proj").reshape(b, t, h_heads, hd)
    k = _proj(x, params, "k_proj").reshape(b, t, kv_heads, hd)
    v = (
        k if spec.k_eq_v
        else _proj(x, params, "v_proj").reshape(b, t, kv_heads, hd)
    )
    if spec.qk_norm:
        q = rms_norm(q, params["q_norm"], spec.rms_norm_eps)
        k = rms_norm(k, params["k_norm"], spec.rms_norm_eps)
    q, k = apply_rotary(q, k, cos, sin)
    attn, _aux = attend(q, k, v)
    attn_out = _proj(attn.reshape(b, t, h_heads * hd), params, "o_proj")
    hidden, k, v = _finish_layer(spec, params, hidden, x, attn_out, k, v)
    return hidden, (k, v)


def _finish_layer(spec, params, hidden, x, attn_out, k_slab, v_slab,
                  lora=None):
    """Residual + MLP tail shared by the dense/flash/paged attention paths."""
    if spec.parallel_attn:
        # falcon: parallel residual. 7b shares one input norm for attention
        # AND the MLP; 40b/180b new-arch uses two (ln_attn already fed the
        # projections above; ln_mlp feeds the MLP)
        if spec.num_ln_in_parallel_attn == 2:
            x_mlp = _norm(hidden, params, "mlp_layernorm", spec)
        else:
            x_mlp = x
        hidden = hidden + attn_out + _mlp(x_mlp, params, spec, lora)
        return hidden, k_slab, v_slab

    if spec.sandwich_norms:
        attn_out = _norm(attn_out, params, "post_attention_layernorm", spec)
        hidden = hidden + attn_out
        x2 = _norm(hidden, params, "pre_feedforward_layernorm", spec)
        mlp_out = _norm(
            _mlp(x2, params, spec, lora), params,
            "post_feedforward_layernorm", spec,
        )
        hidden = hidden + mlp_out
        return hidden, k_slab, v_slab

    hidden = hidden + attn_out
    x2 = _norm(hidden, params, "post_attention_layernorm", spec)
    hidden = hidden + _mlp(x2, params, spec, lora)
    return hidden, k_slab, v_slab
