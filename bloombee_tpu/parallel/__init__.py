"""Parallelism over the device mesh: tp / dp / sp / pp.

The reference's parallelism checklist (SURVEY.md section 2.8) mapped to
TPU-native constructs:

- tensor parallelism: Megatron-style sharded projections with explicit psum
  under shard_map (replaces FlexgenLlamaTensorParallel's per-device CUDA
  streams + NCCL all-reduce, flexgen_tensor_parallel.py:172-828) — rides ICI.
- sequence/context parallelism: ring attention over the "sp" axis (ppermute
  of KV blocks + online softmax) AND Ulysses all-to-all head/sequence
  exchange — the capability the reference LACKS (SURVEY.md section 5
  long-context) and handles only by host offload.
- data parallelism: batch sharding over "dp".
- pipeline parallelism: GPipe micro-batch schedule over the "pp" axis inside
  one jit (the swarm-level span pipeline remains inter-host over the wire).
"""

from bloombee_tpu.parallel.mesh import make_mesh, MeshConfig
from bloombee_tpu.parallel.ring_attention import ring_attention
from bloombee_tpu.parallel.ulysses import ulysses_attention
from bloombee_tpu.parallel.spmd import (
    shard_span_params,
    spmd_block_forward,
    spmd_span_forward,
)

__all__ = [
    "make_mesh",
    "MeshConfig",
    "ring_attention",
    "ulysses_attention",
    "shard_span_params",
    "spmd_block_forward",
    "spmd_span_forward",
]
