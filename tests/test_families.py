"""Model-family parity: full-chain logits vs HF reference for every family.

Port of the reference's per-family parity tier
(/root/reference/tests/test_qwen3_block_parity.py, test_gemma4_*,
test_block_exact_match.py pattern): tiny random HF model -> save -> serve via
one BlockServer -> client logits vs HF forward (atol 1e-3) + greedy token
match.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


def _tiny(family):
    import transformers as tf

    if family == "qwen3":
        config = tf.Qwen3Config(
            hidden_size=64, intermediate_size=128, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, num_hidden_layers=2,
            vocab_size=128, rms_norm_eps=1e-5, tie_word_embeddings=False,
        )
        cls = tf.Qwen3ForCausalLM
    elif family == "mixtral":
        config = tf.MixtralConfig(
            hidden_size=64, intermediate_size=128, num_attention_heads=4,
            num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
            num_local_experts=4, num_experts_per_tok=2, rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )
        cls = tf.MixtralForCausalLM
    elif family == "bloom":
        config = tf.BloomConfig(
            hidden_size=64, n_head=4, n_layer=2, vocab_size=128,
            layer_norm_epsilon=1e-5,
        )
        cls = tf.BloomForCausalLM
    elif family == "falcon":
        config = tf.FalconConfig(
            hidden_size=64, num_attention_heads=4, num_hidden_layers=2,
            vocab_size=128, multi_query=True, parallel_attn=True, bias=False,
            new_decoder_architecture=False, alibi=False,
            layer_norm_epsilon=1e-5,
        )
        cls = tf.FalconForCausalLM
    elif family == "gemma2":
        config = tf.Gemma2Config(
            hidden_size=64, intermediate_size=128, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, num_hidden_layers=2,
            vocab_size=128, rms_norm_eps=1e-5, sliding_window=8,
            query_pre_attn_scalar=16, attn_logit_softcapping=50.0,
            final_logit_softcapping=30.0,
        )
        cls = tf.Gemma2ForCausalLM
    elif family == "falcon40b":
        # new_decoder_architecture: grouped GQA fused QKV + dual parallel
        # LayerNorms (the layout this framework previously rejected loudly)
        config = tf.FalconConfig(
            hidden_size=64, num_attention_heads=4, num_kv_heads=2,
            num_hidden_layers=2, vocab_size=128, bias=False,
            new_decoder_architecture=True, alibi=False,
            layer_norm_epsilon=1e-5,
        )
        cls = tf.FalconForCausalLM
    elif family == "mistral":
        config = tf.MistralConfig(
            hidden_size=64, intermediate_size=128, num_attention_heads=4,
            num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
            rms_norm_eps=1e-5, sliding_window=6,  # < prompt: window active
            tie_word_embeddings=False,
        )
        cls = tf.MistralForCausalLM
    elif family == "qwen2":
        config = tf.Qwen2Config(
            hidden_size=64, intermediate_size=128, num_attention_heads=4,
            num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
            rms_norm_eps=1e-5, tie_word_embeddings=False,
        )
        cls = tf.Qwen2ForCausalLM
    elif family == "qwen3_moe":
        from transformers.models.qwen3_moe import (
            Qwen3MoeConfig,
            Qwen3MoeForCausalLM,
        )

        config = Qwen3MoeConfig(
            hidden_size=64, intermediate_size=128,
            moe_intermediate_size=96, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, num_hidden_layers=2,
            vocab_size=128, rms_norm_eps=1e-5, num_experts=4,
            num_experts_per_tok=2, norm_topk_prob=True,
            decoder_sparse_step=1, tie_word_embeddings=False,
        )
        cls = Qwen3MoeForCausalLM
    else:
        raise KeyError(family)
    torch.manual_seed(0)
    model = cls(config).eval().to(torch.float32)
    return model, config


@pytest.mark.parametrize(
    "family",
    ["qwen3", "mixtral", "bloom", "falcon", "gemma2", "falcon40b",
     "mistral", "qwen2", "qwen3_moe"],
)
def test_family_full_chain_parity(family, tmp_path):
    hf, config = _tiny(family)
    d = str(tmp_path / family)
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = BlockServer(
            model_uid=family, start=0, end=config.num_hidden_layers,
            model_dir=d, registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
        )
        await server.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid=family
        )

        input_ids = (np.arange(10)[None, :] * 7 + 3) % config.vocab_size
        async with model.inference_session(32, 1) as sess:
            out = await sess.step(model.embed(input_ids))
            logits = model.logits(out)
            with torch.no_grad():
                ref = hf(torch.tensor(input_ids)).logits.numpy()
            np.testing.assert_allclose(logits, ref, atol=2e-3, rtol=2e-3)

        ids = await model.generate(input_ids, max_new_tokens=6)
        with torch.no_grad():
            ref_ids = hf.generate(
                torch.tensor(input_ids), max_new_tokens=6, do_sample=False,
            ).numpy()
        np.testing.assert_array_equal(ids, ref_ids)

        await server.stop()
        await reg.stop()

    asyncio.run(run())
