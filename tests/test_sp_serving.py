"""Sequence-parallel SERVING prefill: long prompts spread over an sp mesh
(ring attention), K/V landing in the paged arena, decode continuing on the
ordinary single-chip path. Closes the SURVEY §5 long-context-serving gap
(the reference has no sequence parallelism at all)."""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp
import jax.random as jr

from bloombee_tpu.kv.cache_manager import CacheManager
from bloombee_tpu.models.llama.block import init_block_params
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.parallel.sp_serving import make_sp_mesh
from bloombee_tpu.runtime.executor import SpanExecutor
from bloombee_tpu.utils.tree import stack_params

SPEC = ModelSpec(
    family="llama", hidden_size=32, intermediate_size=64,
    num_attention_heads=4, num_key_value_heads=2, head_dim=8,
    num_hidden_layers=3, vocab_size=64,
)


def _params():
    return stack_params(
        [init_block_params(jr.PRNGKey(i), SPEC) for i in range(3)]
    )


def _run(params, sp, t, monkeypatch, kv_quant=None, decode_steps=3, b=2):
    """Prefill t tokens (+ per-row trailing decode steps); returns
    (prefill_out, decode_outs)."""
    monkeypatch.setenv("BBTPU_SP_MIN_TOKENS", "32")
    monkeypatch.setenv("BBTPU_PAGED_ATTENTION", "0")
    monkeypatch.setenv("BBTPU_FLASH_ATTENTION", "0")

    async def go():
        manager = CacheManager(
            num_layers=3, num_pages=64, page_size=8,
            n_kv_heads=2, head_dim=8, dtype=jnp.float32, quant=kv_quant,
        )
        ex = SpanExecutor(
            params, SPEC, manager, compute_dtype=jnp.float32,
            max_chunk_tokens=64,
            sp_mesh=make_sp_mesh(sp) if sp > 1 else None,
        )
        rng = np.random.default_rng(0)
        hidden = rng.standard_normal((b, t, 32)).astype(np.float32) * 0.1
        steps = [
            rng.standard_normal((b, 1, 32)).astype(np.float32) * 0.1
            for _ in range(decode_steps)
        ]
        async with manager.allocate(b, t + decode_steps + 1) as handle:
            pre = ex.prefill(handle, hidden)
            assert list(manager.context_lens(handle)) == [t] * b
            outs = [ex.decode(handle, s) for s in steps]
        return pre, outs

    return asyncio.run(go())


@pytest.mark.parametrize("t", [64, 72], ids=["aligned", "needs_pad"])
def test_sp_prefill_matches_single_chip(monkeypatch, t):
    """sp=4 prefill output AND the arena it leaves behind must match the
    single-chip path: decode steps after it are the proof the KV landed
    correctly (t=72 exercises the pad-to-multiple-of-sp path)."""
    params = _params()
    ref_pre, ref_outs = _run(params, 1, t, monkeypatch)
    sp_pre, sp_outs = _run(params, 4, t, monkeypatch)
    np.testing.assert_allclose(
        np.asarray(sp_pre, np.float32), np.asarray(ref_pre, np.float32),
        atol=3e-5, rtol=3e-5,
    )
    for a, b_ in zip(sp_outs, ref_outs):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            atol=3e-5, rtol=3e-5,
        )


def test_sp_rejects_quantized_arena():
    """int4 arenas attend QUANTIZED KV during single-chip prefill (each
    chunk reads back what it just wrote); ring attention attends full
    precision — a numeric contract change. The combination must fail at
    STARTUP (a silent fallback would still pin the replicated sp param
    copies while never parallelizing anything)."""
    params = _params()
    manager = CacheManager(
        num_layers=3, num_pages=64, page_size=8,
        n_kv_heads=2, head_dim=8, dtype=jnp.float32, quant="int4",
    )
    with pytest.raises(ValueError, match="quantized KV arena"):
        SpanExecutor(
            params, SPEC, manager, compute_dtype=jnp.float32,
            sp_mesh=make_sp_mesh(2),
        )


def test_sp_short_prefill_stays_single_chip(monkeypatch):
    """Below BBTPU_SP_MIN_TOKENS the chunked single-chip path runs (the
    collectives would dominate tiny prompts)."""
    params = _params()
    monkeypatch.setenv("BBTPU_SP_MIN_TOKENS", "4096")

    async def go():
        manager = CacheManager(
            num_layers=3, num_pages=64, page_size=8,
            n_kv_heads=2, head_dim=8, dtype=jnp.float32,
        )
        ex = SpanExecutor(
            params, SPEC, manager, compute_dtype=jnp.float32,
            sp_mesh=make_sp_mesh(2),
        )
        called = {"sp": False}
        orig = ex._sp_prefill
        ex._sp_prefill = lambda *a, **k: called.__setitem__("sp", True) or orig(*a, **k)
        rng = np.random.default_rng(1)
        async with manager.allocate(1, 64) as handle:
            ex.prefill(
                handle,
                rng.standard_normal((1, 32, 32)).astype(np.float32),
            )
        assert not called["sp"]

    asyncio.run(go())


def test_sp_block_server_e2e(tmp_path):
    """Full swarm path with an sp=2 server: a long-prompt greedy generate
    must match HF (the prefill runs over the sp mesh, decode single-chip)."""
    import os

    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        max_position_embeddings=512, tie_word_embeddings=False,
    )
    torch.manual_seed(3)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)

    async def run():
        os.environ["BBTPU_SP_MIN_TOKENS"] = "64"
        try:
            reg = RegistryServer(host="127.0.0.1")
            await reg.start()

            def rc():
                return RegistryClient("127.0.0.1", reg.port)

            server = BlockServer(
                model_uid="t", start=0, end=2, model_dir=str(tmp_path),
                registry=rc(), compute_dtype=jnp.float32, num_pages=64,
                page_size=4, sp=2,
            )
            await server.start()
            dm = DistributedModelForCausalLM.from_pretrained(
                str(tmp_path), rc(), model_uid="t"
            )
            rng = np.random.default_rng(9)
            ids_in = rng.integers(0, config.vocab_size, size=(1, 100))
            ids = await dm.generate(
                ids_in, max_new_tokens=5, server_decode=False
            )
            with torch.no_grad():
                ref = model.generate(
                    torch.tensor(ids_in), max_new_tokens=5, do_sample=False,
                    use_cache=True,
                ).numpy()
            np.testing.assert_array_equal(ids, ref)
            await server.stop()
            await reg.stop()
        finally:
            del os.environ["BBTPU_SP_MIN_TOKENS"]

    asyncio.run(run())


def test_sp_not_eligible_for_parked_session(monkeypatch):
    """A host-parked session's table length reads 0 but its KV lives in
    the park — sp prefill must NOT treat it as fresh (it would write from
    position 0 and orphan the parked KV; confirmed-by-repro review
    finding)."""
    params = _params()
    monkeypatch.setenv("BBTPU_SP_MIN_TOKENS", "8")

    async def go():
        manager = CacheManager(
            num_layers=3, num_pages=64, page_size=8,
            n_kv_heads=2, head_dim=8, dtype=jnp.float32,
        )
        ex = SpanExecutor(
            params, SPEC, manager, compute_dtype=jnp.float32,
            sp_mesh=make_sp_mesh(2),
        )
        rng = np.random.default_rng(0)
        async with manager.allocate(1, 64) as handle:
            assert ex._sp_eligible(handle, 16, True, None, None)
            ex._step(
                handle,
                rng.standard_normal((1, 16, 32)).astype(np.float32),
                commit=True,
            )
            assert not ex._sp_eligible(handle, 16, True, None, None)
            manager.park_sequence(handle.seq_ids[0])
            # table length now reads 0, KV is parked: still NOT fresh
            assert not np.any(manager.context_lens(handle))
            assert not ex._sp_eligible(handle, 16, True, None, None)

    asyncio.run(go())
